"""graftlint shared core: repo model, suppressions, findings, call graph.

The checkers (tools/graftlint/checks/) enforce the invariants the serving
hot path depends on (docs/LINTING.md); this module gives them one parsed
view of the repo so every checker agrees on what a "function", a "jitted
callable", or a "hot-path function" is.

Design stance: checkers are PRECISION-FIRST. A finding should be worth a
human's time, so the matchers under-approximate (a dynamic dispatch or a
function value stored in a local is invisible to them) and the documented
conventions (``# graftlint: hot``, ``# graftlint: ok(<rule>)``) close the
gap explicitly instead of heuristics guessing.

Analysis units come at two granularities:

- ``FunctionInfo`` — outermost functions and methods. Nested defs and
  lambdas belong to their outermost enclosing function: the hot-path walk
  and the host-sync scan treat the whole lexical body as one unit.
- ``Unit`` — every def/lambda separately, with parent links. The
  pallas-guard taint analysis needs this resolution: a nested ``scan``
  helper that reaches a kernel must not taint its enclosing ``search``
  when every reference to it is wrapped in ``pallas_guarded``.
"""

from __future__ import annotations

import ast
import dataclasses
import functools
import io
import os
import re
import tokenize
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Set, Tuple

SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*ok\(([^)]*)\)")
HOT_RE = re.compile(r"#\s*graftlint:\s*hot\b")
# ``graftlint: atomic(attr[, attr2])`` comment markers — a reviewed
# declaration that the named attribute(s) of the lexically enclosing class
# are benign to access without a lock across threads (monotonic counters,
# publish-once flags, single-machine-word reads whose staleness is
# acceptable). Consumed by the shared-state-race checker; a marker that
# waives no live cross-root access is itself a finding (the atomic-rot
# half of the suppression audit).
ATOMIC_RE = re.compile(r"#\s*graftlint:\s*atomic\(([^)]*)\)")

# call-graph roots for the hot-path walk (module path suffix, qualname);
# any function annotated `# graftlint: hot` is an additional root.
# Index.search_batched is the scheduler's launch target (the merged-window
# serving path reaches the engine through it, not through Index.search),
# and the mesh search entry points are the one-launch serving programs —
# rooting them keeps the host-sync checker policing the multi-chip path
# even where dynamic dispatch (scheduler callbacks, tpu_index attribute
# calls) hides the edges from the name-based walk.
#
# The roots themselves live in utils/jitreg.py (the jit-entry registry):
# the registry, this AST tier and the IR tier all describe the same
# compiled-program surface, so there is exactly ONE declaration of it.
# The registry file keeps its declarations as pure literals so this
# stdlib-only tier can AST-parse it without importing jax.

_JITREG_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), os.pardir, os.pardir,
    "distributed_faiss_tpu", "utils", "jitreg.py")

# IR-tier rule names (tools/graftlint/ir). Declared here so the AST tier
# can recognize ok(ir-*) suppressions as known — and hold them dormant
# (not stale) on runs where the IR tier didn't execute.
IR_RULES = frozenset({
    "ir-device-residency", "ir-dtype", "ir-const-capture",
    "ir-bucket-budget", "ir-trace-failure",
})


@functools.lru_cache(maxsize=1)
def _registry_literals() -> Dict[str, object]:
    """AST-parse utils/jitreg.py for its declarative literals."""
    with open(_JITREG_PATH, "r", encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=_JITREG_PATH)
    out: Dict[str, object] = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id in ("HOT_ROOTS", "REGISTRY",
                                           "PURE_CALLBACK_ALLOWLIST")):
            out[node.targets[0].id] = ast.literal_eval(node.value)
    missing = {"HOT_ROOTS", "REGISTRY"} - set(out)
    if missing:
        raise RuntimeError(
            f"utils/jitreg.py is missing literal declarations {sorted(missing)}"
            " — the AST tier derives its hot-root/launch views from them")
    return out


def registry_rows() -> Tuple[dict, ...]:
    """The jit-entry registry rows, as literals (no jax import)."""
    return tuple(_registry_literals()["REGISTRY"])


def registry_launch_names() -> frozenset:
    """Qualnames of every registered jitted launch target — unioned into
    the blocking checker's launch-name set so a registered kernel carries
    launch semantics even where dynamic dispatch hides the jit decoration
    from the per-module AST scan."""
    return frozenset(r["qualname"] for r in registry_rows() if r.get("trace"))


HOT_ROOTS: Tuple[Tuple[str, str], ...] = tuple(
    (str(p), str(q)) for p, q in _registry_literals()["HOT_ROOTS"])

# module aliases that resolve to code outside this repo: attribute calls
# rooted here are never treated as calls to repo functions
EXTERNAL_ROOTS = frozenset({
    "jax", "jnp", "lax", "pl", "pltpu", "np", "numpy", "os", "np_mod",
    "threading", "functools", "itertools", "logging", "pickle", "json",
    "socket", "struct", "time", "re", "math", "selectors", "pathlib",
    "ctypes", "subprocess", "sys", "random",
})

NUMPY_ALIASES = frozenset({"np", "numpy"})

# names of the utils.lockdep factory functions: `self.x = lockdep.lock(...)`
# creates a (possibly instrumented) lock exactly like `threading.Lock()`.
# Lock detection must recognize both spellings or wiring the runtime
# witness would silently blind every lock checker (the frame-protocol
# stale-pin audit exists to catch exactly that class of drift).
LOCKDEP_FACTORIES = frozenset({"lock", "rlock", "condition"})
_LOCK_CTORS = frozenset({"Lock", "RLock", "Condition"})


def is_lock_ctor(node: ast.AST) -> bool:
    """True when ``node`` is a lock-creating call: ``threading.Lock()`` /
    ``RLock()`` / ``Condition()``, or a ``lockdep.lock/rlock/condition(...)``
    factory call (utils/lockdep.py — plain primitive when DFT_LOCKDEP is
    off, instrumented witness when on)."""
    if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
        return False
    if node.func.attr in _LOCK_CTORS:
        return True
    return (node.func.attr in LOCKDEP_FACTORIES
            and attr_root(node.func) == "lockdep")


def lock_attrs(class_node) -> set:
    """Attributes of ``self`` assigned a lock anywhere in the class body
    (see ``is_lock_ctor`` for what counts as a lock)."""
    locks = set()
    for node in ast.walk(class_node):
        if not isinstance(node, ast.Assign):
            continue
        if not is_lock_ctor(node.value):
            continue
        for t in node.targets:
            if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                locks.add(t.attr)
    return locks


def lock_context_events(method_node, lock_names):
    """Walk one method body under the lock-discipline lexical model,
    yielding two event kinds:

    - ``("acquire", lock_attr, held_before, node)`` — a ``with
      self.<lock>:`` item, with the ordered tuple of locks already held
      lexically at that point (multi-item withs acquire left to right);
    - ``("node", ast_node, held)`` — every other AST node, with the
      ordered tuple of locks held around it.

    Lambdas inherit the surrounding lock context (they run inline);
    nested ``def``s reset it (they usually run later on another thread).
    """

    def self_lock(expr):
        if (isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and expr.attr in lock_names):
            return expr.attr
        return None

    def visit(node, held):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            # items evaluate left to right, each AFTER the previous items'
            # locks are acquired — so a later item's context expression
            # (e.g. `with self.lock, sock.accept() as c:`) runs with the
            # earlier locks held
            new_held = list(held)
            for item in node.items:
                attr = self_lock(item.context_expr)
                if attr is not None:
                    yield ("acquire", attr, tuple(new_held), item.context_expr)
                    if attr not in new_held:
                        new_held.append(attr)
                else:
                    yield from visit(item.context_expr, tuple(new_held))
            for sub in node.body:
                yield from visit(sub, tuple(new_held))
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in node.body:
                yield from visit(sub, ())  # runs later: no inherited locks
            return
        if isinstance(node, ast.Lambda):
            yield from visit(node.body, held)  # runs inline: inherits locks
            return
        yield ("node", node, held)
        for child in ast.iter_child_nodes(node):
            yield from visit(child, held)

    for stmt in method_node.body:
        yield from visit(stmt, ())

# method names excluded as hot-path call-graph edges: ubiquitous container/
# builtin method names that would otherwise alias repo functions (a
# `seen.add(x)` inside a hot function must not mark every `Index.add` hot —
# ingest paths are reached from `add_batch`, not `search`)
HOT_EDGE_STOPLIST = frozenset({
    "add", "append", "extend", "update", "pop", "get", "set", "clear",
    "remove", "close", "record", "join", "split", "copy", "items", "keys",
    "values", "wait", "acquire", "release", "put",
})


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"


@dataclasses.dataclass(frozen=True)
class JitInfo:
    static_names: frozenset
    static_nums: Tuple[int, ...]


def _is_jit_ref(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "jit") or (
        isinstance(node, ast.Name) and node.id == "jit"
    )


def _const_items(node: ast.AST) -> list:
    if isinstance(node, ast.Constant):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts if isinstance(e, ast.Constant)]
    return []


def jit_info_from_call(call: ast.Call) -> Optional[JitInfo]:
    """JitInfo for ``jax.jit(...)`` / ``functools.partial(jax.jit, ...)``
    call expressions; None when the call is neither."""
    f = call.func
    is_partial = (isinstance(f, ast.Attribute) and f.attr == "partial") or (
        isinstance(f, ast.Name) and f.id == "partial"
    )
    inner_jit = is_partial and call.args and _is_jit_ref(call.args[0])
    if not (_is_jit_ref(f) or inner_jit):
        return None
    names: frozenset = frozenset()
    nums: Tuple[int, ...] = ()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            names = frozenset(v for v in _const_items(kw.value) if isinstance(v, str))
        elif kw.arg == "static_argnums":
            nums = tuple(v for v in _const_items(kw.value) if isinstance(v, int))
    return JitInfo(names, nums)


def decorator_jit_info(node) -> Optional[JitInfo]:
    for dec in node.decorator_list:
        if _is_jit_ref(dec):
            return JitInfo(frozenset(), ())
        if isinstance(dec, ast.Call):
            info = jit_info_from_call(dec)
            if info is not None:
                return info
    return None


def call_name(call: ast.Call) -> Optional[str]:
    """Bare name of a call target: ``f(...)`` -> "f", ``a.b.c(...)`` -> "c"."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def attr_root(node: ast.AST) -> Optional[str]:
    """Leftmost Name of an attribute chain: ``a.b.c`` -> "a"."""
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def dotted(node: ast.AST) -> Optional[str]:
    """Full dotted name of Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


class Unit:
    """One def/lambda, at full nesting resolution (pallas-guard taint)."""

    __slots__ = (
        "module", "name", "qualname", "node", "parent", "lineno",
        "has_pallas_call", "calls_pallas_guarded",
    )

    def __init__(self, module, name, qualname, node, parent, lineno):
        self.module = module
        self.name = name  # None for lambdas
        self.qualname = qualname
        self.node = node
        self.parent = parent
        self.lineno = lineno
        self.has_pallas_call = False
        self.calls_pallas_guarded = False


class FunctionInfo:
    """One outermost function/method (nested defs included in its body)."""

    __slots__ = (
        "module", "name", "qualname", "cls", "node", "lineno", "jit",
        "called_names", "hot", "hot_annotated",
    )

    def __init__(self, module, name, qualname, cls, node):
        self.module = module
        self.name = name
        self.qualname = qualname
        self.cls = cls  # enclosing class name or None
        self.node = node
        self.lineno = node.lineno
        self.jit = decorator_jit_info(node)
        self.called_names: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                n = call_name(sub)
                if n:
                    self.called_names.add(n)
        first = min([d.lineno for d in node.decorator_list] + [node.lineno])
        self.hot_annotated = any(
            ln in module.hot_lines for ln in range(first - 1, node.lineno + 1)
        )
        self.hot = False


def module_level_stmts(stmts):
    """Yield defs/classes at module (or class) level, descending into
    statement blocks (if/try/with/for/while — version gates, availability
    guards) but never into function bodies."""
    for s in stmts:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            yield s
        elif isinstance(s, (ast.If, ast.Try, ast.With, ast.For, ast.While,
                            ast.AsyncWith, ast.AsyncFor)):
            blocks = [getattr(s, "body", []), getattr(s, "orelse", []),
                      getattr(s, "finalbody", [])]
            blocks += [h.body for h in getattr(s, "handlers", [])]
            for blk in blocks:
                yield from module_level_stmts(blk)


class ModuleInfo:
    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.suppressions: Dict[int, Set[str]] = {}
        self.hot_lines: Set[int] = set()
        self.atomic_marks: Dict[int, Set[str]] = {}
        for i, text in self._comment_lines():
            m = SUPPRESS_RE.search(text)
            if m:
                self.suppressions[i] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()
                }
            if HOT_RE.search(text):
                self.hot_lines.add(i)
            m = ATOMIC_RE.search(text)
            if m:
                self.atomic_marks[i] = {
                    a.strip() for a in m.group(1).split(",") if a.strip()
                }
        # alias -> imported module dotted path (for internal/external calls)
        self.import_aliases: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_aliases[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.import_aliases[a.asname or a.name] = (
                        f"{node.module}.{a.name}"
                    )
        self.functions: List[FunctionInfo] = []
        self.classes: List[ast.ClassDef] = []
        self.units: List[Unit] = []
        self._collect()

    def _collect(self) -> None:
        for node in module_level_stmts(self.tree.body):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.append(
                    FunctionInfo(self, node.name, node.name, None, node))
            elif isinstance(node, ast.ClassDef):
                self.classes.append(node)
                for sub in module_level_stmts(node.body):
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self.functions.append(FunctionInfo(
                            self, sub.name, f"{node.name}.{sub.name}",
                            node.name, sub))
        for fi in self.functions:
            self._collect_units(fi.node, fi.qualname, None)

    def _collect_units(self, node, qualprefix: str, parent: Optional[Unit]):
        name = getattr(node, "name", None)
        qual = qualprefix if parent is None else f"{qualprefix}.{name or '<lambda>'}"
        unit = Unit(self, name, qual, node, parent, node.lineno)
        self.units.append(unit)
        body = node.body if not isinstance(node, ast.Lambda) else [node.body]

        def scan(n):
            if isinstance(n, ast.Call):
                cn = call_name(n)
                if cn == "pallas_call":
                    unit.has_pallas_call = True
                if cn == "pallas_guarded":
                    unit.calls_pallas_guarded = True
            for child in ast.iter_child_nodes(n):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    self._collect_units(child, qual, unit)
                else:
                    scan(child)

        for stmt in body:
            scan(stmt)

    # -- suppression / classification helpers ----------------------------

    def _comment_lines(self):
        """(line, text) for every line carrying a real ``#`` COMMENT token.
        Annotations live in comments; scanning raw source lines would also
        match docstring/string-literal mentions of the syntax (e.g. the
        examples in this package's own docstrings), which must neither
        create suppressions nor trip the suppression-rot audit. Falls
        back to the raw line scan only when the module fails to tokenize
        (it already parsed, so this is near-unreachable)."""
        try:
            out = []
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.source).readline):
                if tok.type == tokenize.COMMENT:
                    out.append((tok.start[0], tok.string))
            return out
        except (tokenize.TokenError, IndentationError):  # pragma: no cover
            return [(i, t) for i, t in enumerate(self.lines, 1) if "#" in t]

    def match_suppression(self, rule: str, line: int) -> Optional[int]:
        """Comment line of the ``# graftlint: ok(<rule>)`` that covers a
        finding at ``line`` — its own line, the line above, or on/above
        the ``def`` line of an enclosing function (which scopes the
        suppression to the whole function). None when unsuppressed. The
        returned line is how ``lint`` records which suppressions earned
        their keep (the suppression-rot audit flags the rest)."""
        for ln in (line, line - 1):
            rules = self.suppressions.get(ln)
            if rules and (rule in rules or "all" in rules):
                return ln
        for u in self.units:
            end = getattr(u.node, "end_lineno", u.lineno)
            if not (u.lineno <= line <= end):
                continue
            for ln in (u.lineno, u.lineno - 1):
                rules = self.suppressions.get(ln)
                if rules and (rule in rules or "all" in rules):
                    return ln
        return None

    def suppressed(self, rule: str, line: int) -> bool:
        return self.match_suppression(rule, line) is not None

    def internal_alias(self, name: str) -> bool:
        """True when ``name`` is an import alias of a module in this repo
        (anything under the repo's own top-level packages)."""
        target = self.import_aliases.get(name)
        if target is None:
            return False
        root = target.split(".")[0]
        return root in ("distributed_faiss_tpu", "tools") or target.startswith(".")

    def is_ops(self) -> bool:
        return "/ops/" in self.relpath or self.relpath.startswith("ops/")


class RepoModel:
    def __init__(self, modules: List[ModuleInfo], subset: bool = False):
        # subset=True: a partial lint (`--changed`) — cross-artifact rules
        # that are only decidable against the full package (knob/doc
        # drift, the suppression-rot audit) must gate themselves off
        self.subset = subset
        self.modules = modules
        self.functions: List[FunctionInfo] = [
            f for m in modules for f in m.functions
        ]
        self.units: List[Unit] = [u for m in modules for u in m.units]
        self.by_name: Dict[str, List[FunctionInfo]] = defaultdict(list)
        for f in self.functions:
            self.by_name[f.name].append(f)
        self.jitted_names: Set[str] = {f.name for f in self.functions if f.jit}
        self._mark_hot()

    def _mark_hot(self) -> None:
        roots = [f for f in self.functions if f.hot_annotated]
        for suffix, qualname in HOT_ROOTS:
            roots += [
                f for f in self.functions
                if f.qualname == qualname and f.module.relpath.endswith(suffix)
            ]
        seen: Set[int] = set()
        stack = list(roots)
        while stack:
            f = stack.pop()
            if id(f) in seen:
                continue
            seen.add(id(f))
            f.hot = True
            for name in f.called_names:
                if name in HOT_EDGE_STOPLIST:
                    continue
                for g in self.by_name.get(name, ()):
                    if id(g) not in seen:
                        stack.append(g)


def collect_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [
                d for d in sorted(dirnames)
                if not d.startswith(".") and d != "__pycache__"
            ]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


def build_model(paths: Iterable[str], subset: bool = False) -> RepoModel:
    modules = []
    for path in collect_files(paths):
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        modules.append(ModuleInfo(path, os.path.relpath(path), source))
    return RepoModel(modules, subset=subset)


# ---------------------------------------------------------- thread-root model
#
# The shared-state-race checker (checks/races.py) needs a whole-program
# answer to "which THREAD touches this attribute, holding what?". The
# thread-root model is that answer: it enumerates every thread ENTRY POINT
# the package creates — named ``threading.Thread`` targets (including
# nested-def targets like the engine's save watcher), ``ThreadPoolExecutor``
# ``submit``/``map`` callables, and the public-API caller root (the user's
# own thread entering any public method of a lock-owning class) — then
# walks the call graph from each root with an interprocedural LOCKSET:
# the lexical ``with self.<lock>`` model (lock_context_events) extended by
# held-at-entry propagation, so a helper only ever called under a lock
# carries that lock into its accesses. Where a function is reachable under
# several locksets within one root, the entry lockset is the INTERSECTION
# (a lock held on every path), which is the conservative direction for
# race detection.
#
# Resolution is the package's precision-first shape — bare names prefer
# same-module definitions (else a globally unique one), ``self.m()``
# dispatches exactly — plus one deliberate loosening shared with the
# blocking checker: an attribute call whose method name is globally unique
# (and not stoplisted / rooted in an external module) resolves, because
# watcher threads reach the engine through parameters
# (``engine.compact()``), which exact resolution cannot see. Spawn sites
# themselves (``Thread(target=...)``, ``pool.submit(fn)``) never create a
# same-root call edge — the callee runs on the OTHER root.

API_ROOT = "api"

_SPAWN_METHODS = frozenset({"submit", "map"})

MUTATOR_METHODS = frozenset({
    "append", "extend", "insert", "pop", "popitem", "remove", "clear",
    "update", "setdefault", "add", "discard", "sort", "reverse",
})

_SKIP_WALK_METHODS = frozenset({"__init__", "__new__", "__del__"})


@dataclasses.dataclass(frozen=True)
class SharedAccess:
    """One ``self.<attr>`` touch attributed to a thread root."""

    cls: str
    attr: str
    write: bool
    path: str
    line: int
    col: int
    locks: frozenset  # qualified "Cls.lock" keys held (lexical + entry)
    root: str         # thread-root label ("api", "thread:...", "pool:...")
    func: str         # qualname of the accessing function (provenance)


# expressions that build a plain container: a ``.append``/``.update``-class
# call on an attribute holding one of these is a container MUTATION (a
# write for race purposes); the same method name on a domain object
# (``self.membership.remove(pos)`` — MembershipTable's internally-locked
# method) is an ordinary call and must not be misread as a torn write
_CONTAINER_CTORS = frozenset({
    "list", "dict", "set", "deque", "defaultdict", "OrderedDict", "Counter",
})


def _container_assigned_attrs(class_node) -> set:
    """Attributes of ``self`` assigned a container literal/constructor
    anywhere in the class body (including ``__init__``)."""
    out = set()
    for node in ast.walk(class_node):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        is_container = isinstance(v, (
            ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
            ast.SetComp,
        )) or (isinstance(v, ast.Call) and call_name(v) in _CONTAINER_CTORS)
        if not is_container:
            continue
        for t in node.targets:
            if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                out.add(t.attr)
    return out


def _is_thread_ctor_call(call: ast.Call, mod: ModuleInfo) -> bool:
    if dotted(call.func) == "threading.Thread":
        return True
    if isinstance(call.func, ast.Name):
        return mod.import_aliases.get(call.func.id) == "threading.Thread"
    return False


class ThreadRootModel:
    """Thread roots + per-root shared-state accesses over one RepoModel."""

    def __init__(self, model: RepoModel):
        self.model = model
        # label -> (kind, relpath, line): spawn-site provenance per root
        self.roots: Dict[str, Tuple[str, str, int]] = {}
        self.accesses: List[SharedAccess] = []
        self._class_locks: Dict[Tuple[int, str], set] = {}
        self._container_attrs: Dict[Tuple[int, str], set] = {}
        for mod in model.modules:
            for cnode in mod.classes:
                attrs = lock_attrs(cnode)
                if attrs:
                    self._class_locks[(id(mod), cnode.name)] = attrs
                self._container_attrs[(id(mod), cnode.name)] = (
                    _container_assigned_attrs(cnode))
        self._analyzed: Dict[int, Tuple[list, list]] = {}
        self._fns: Dict[int, FunctionInfo] = {}
        for label, seeds in self._enumerate_roots().items():
            self._walk(label, seeds)
        self.accesses.sort(key=lambda a: (a.path, a.line, a.col, a.root))

    # ------------------------------------------------------------ resolution

    def _ref_targets(self, expr, fi: FunctionInfo) -> List[FunctionInfo]:
        """Functions a callable REFERENCE (a Thread target, a submit arg)
        may denote — includes nested defs of the enclosing function (the
        save watcher's ``_watch``), which close over the method's scope."""
        model = self.model
        if isinstance(expr, ast.Name):
            for sub in ast.walk(fi.node):
                if (isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and sub.name == expr.id and sub is not fi.node):
                    return [FunctionInfo(
                        fi.module, sub.name, f"{fi.qualname}.{sub.name}",
                        fi.cls, sub)]
            cands = model.by_name.get(expr.id, [])
            same = [g for g in cands if g.module is fi.module]
            if same:
                return same
            return list(cands) if len(cands) == 1 else []
        if isinstance(expr, ast.Attribute):
            if (isinstance(expr.value, ast.Name) and expr.value.id == "self"
                    and fi.cls is not None):
                exact = [g for g in model.by_name.get(expr.attr, ())
                         if g.module is fi.module and g.cls == fi.cls]
                if exact:
                    return exact
            if attr_root(expr) in EXTERNAL_ROOTS:
                return []
            if expr.attr in HOT_EDGE_STOPLIST:
                return []
            cands = model.by_name.get(expr.attr, [])
            return list(cands) if len(cands) == 1 else []
        return []

    def _call_targets(self, call: ast.Call, fi: FunctionInfo):
        """Same-root callees of one call site (spawn sites excluded: their
        callable runs on the root the spawn created, not this one). Bare
        names resolve same-module-first (never into nested defs — those
        are already walked inline by the lexical model)."""
        f = call.func
        if (isinstance(f, ast.Attribute) and f.attr in _SPAWN_METHODS
                and call.args and self._ref_targets(call.args[0], fi)):
            return []
        if isinstance(f, ast.Name):
            if f.id in HOT_EDGE_STOPLIST:
                return []
            cands = self.model.by_name.get(f.id, [])
            same = [g for g in cands if g.module is fi.module]
            if same:
                return same
            return list(cands) if len(cands) == 1 else []
        if isinstance(f, ast.Attribute):
            return self._ref_targets(f, fi)
        return []

    # ------------------------------------------------------------ enumeration

    def _enumerate_roots(self) -> Dict[str, List[FunctionInfo]]:
        seeds: Dict[str, List[FunctionInfo]] = defaultdict(list)
        seen_nodes: Dict[str, Set[int]] = defaultdict(set)

        def add(label, kind, fn, relpath, line):
            if id(fn.node) in seen_nodes[label]:
                return
            seen_nodes[label].add(id(fn.node))
            self.roots.setdefault(label, (kind, relpath, line))
            seeds[label].append(fn)

        for fi in self.model.functions:
            # the public-API caller root: a user thread may enter any
            # public method of a lock-owning class (and any public
            # module-level function) directly
            public = not fi.name.startswith("_")
            if public and (fi.cls is None or (id(fi.module), fi.cls)
                           in self._class_locks):
                add(API_ROOT, "api", fi, fi.module.relpath, fi.lineno)
            for sub in ast.walk(fi.node):
                if not isinstance(sub, ast.Call):
                    continue
                if _is_thread_ctor_call(sub, fi.module):
                    target = next((kw.value for kw in sub.keywords
                                   if kw.arg == "target"), None)
                    if target is None:
                        continue
                    for g in self._ref_targets(target, fi):
                        add(f"thread:{g.qualname}", "thread", g,
                            fi.module.relpath, sub.lineno)
                elif (isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in _SPAWN_METHODS and sub.args
                        and attr_root(sub.func) not in EXTERNAL_ROOTS):
                    for g in self._ref_targets(sub.args[0], fi):
                        add(f"pool:{g.qualname}", "pool", g,
                            fi.module.relpath, sub.lineno)
        return seeds

    # ------------------------------------------------------------ the walk

    def _analyze(self, fn: FunctionInfo):
        """Cached per-function scan: (raw accesses, raw call edges), each
        carrying the LEXICALLY held own-class locks at the site."""
        cached = self._analyzed.get(id(fn.node))
        if cached is not None:
            return cached
        lock_names = self._class_locks.get(
            (id(fn.module), fn.cls), frozenset()) if fn.cls else frozenset()
        containers = self._container_attrs.get(
            (id(fn.module), fn.cls), frozenset()) if fn.cls else frozenset()
        accesses: list = []   # (attr, write, line, col, held-tuple)
        calls: list = []      # (callee FunctionInfo, held-tuple)
        skip_reads: Set[int] = set()  # inner attr nodes of write wrappers

        for ev in lock_context_events(fn.node, lock_names):
            if ev[0] != "node":
                continue
            _, node, held = ev
            if isinstance(node, ast.Attribute):
                if not (isinstance(node.value, ast.Name)
                        and node.value.id == "self"):
                    continue
                attr = node.attr
                if (attr in lock_names or attr.startswith("__")):
                    continue
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    accesses.append((attr, True, node.lineno,
                                     node.col_offset, held))
                elif id(node) not in skip_reads:
                    accesses.append((attr, False, node.lineno,
                                     node.col_offset, held))
            elif isinstance(node, ast.Subscript) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                base = node.value
                if (isinstance(base, ast.Attribute)
                        and isinstance(base.value, ast.Name)
                        and base.value.id == "self"
                        and base.attr not in lock_names):
                    accesses.append((base.attr, True, node.lineno,
                                     node.col_offset, held))
                    skip_reads.add(id(base))
            elif isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute)
                        and f.attr in MUTATOR_METHODS
                        and isinstance(f.value, ast.Attribute)
                        and isinstance(f.value.value, ast.Name)
                        and f.value.value.id == "self"
                        and f.value.attr in containers
                        and f.value.attr not in lock_names):
                    accesses.append((f.value.attr, True, node.lineno,
                                     node.col_offset, held))
                    skip_reads.add(id(f.value))
                for g in self._call_targets(node, fn):
                    if g.name not in _SKIP_WALK_METHODS:
                        calls.append((g, held))
        result = (accesses, calls)
        self._analyzed[id(fn.node)] = result
        self._fns[id(fn.node)] = fn
        return result

    def _walk(self, label: str, seeds: List[FunctionInfo]) -> None:
        def qualify(fn, held):
            return frozenset(f"{fn.cls}.{h}" for h in held)

        entry: Dict[int, frozenset] = {}
        fns: Dict[int, FunctionInfo] = {}
        work: List[FunctionInfo] = []
        for fn in seeds:
            entry[id(fn.node)] = frozenset()
            fns[id(fn.node)] = fn
            work.append(fn)
        # phase 1: propagate held-at-entry locksets to a fixpoint
        # (intersection merge — only a lock held on EVERY path counts)
        while work:
            fn = work.pop()
            eff_base = entry[id(fn.node)]
            _, calls = self._analyze(fn)
            for g, held in calls:
                eff = eff_base | qualify(fn, held)
                cur = entry.get(id(g.node))
                if cur is None:
                    entry[id(g.node)] = eff
                    fns[id(g.node)] = g
                    work.append(g)
                else:
                    merged = cur & eff
                    if merged != cur:
                        entry[id(g.node)] = merged
                        work.append(fns[id(g.node)])
        # phase 2: record every self.<attr> access with its final lockset.
        # Scope: methods of LOCK-OWNING classes only (the lock-discipline
        # scope) — lock-less helper classes (frame decode cursors, the
        # tombstone set) are either method-local or reached exclusively
        # through a lock-owning owner whose pinned attribute already
        # carries the guarantee
        for nid, base in entry.items():
            fn = fns[nid]
            if fn.cls is None or (
                    id(fn.module), fn.cls) not in self._class_locks:
                continue
            accesses, _ = self._analyze(fn)
            for attr, write, line, col, held in accesses:
                self.accesses.append(SharedAccess(
                    fn.cls, attr, write, fn.module.relpath, line, col,
                    frozenset(base | qualify(fn, held)), label, fn.qualname))


def thread_root_model(model: RepoModel) -> ThreadRootModel:
    """The (memoized) thread-root model for one RepoModel."""
    cached = getattr(model, "_thread_root_model", None)
    if cached is None:
        cached = ThreadRootModel(model)
        model._thread_root_model = cached
    return cached


SUPPRESSION_AUDIT_RULE = "unused-suppression"


def _audit_suppressions(model: RepoModel, used: Dict[int, Set[int]],
                        known_rules: Set[str],
                        dormant_rules: Set[str] = frozenset()) -> List[Finding]:
    """The suppression-rot audit: every ``# graftlint: ok(<rule>)`` comment
    must either suppress a live finding THIS run or name a rule that no
    longer exists — a suppression that does neither is itself a finding,
    so the reviewed-waiver inventory can't rot into a pile of comments
    nobody can tell apart from load-bearing ones. Deliberately-dormant
    waivers (e.g. version-gated code paths) opt out explicitly with
    ``ok(unused-suppression)`` beside them — which that very audit then
    tracks like any other suppression."""
    out: List[Finding] = []
    for mod in model.modules:
        used_lines = used.get(id(mod), set())
        markers = []  # pure ok(unused-suppression) lines, audited last
        for line in sorted(mod.suppressions):
            if line in used_lines:
                continue
            rules = mod.suppressions[line]
            if rules & dormant_rules:
                # names a rule belonging to a tier that did not run this
                # invocation (the IR tier on an AST-only lint): whether the
                # suppression is live is undecidable here, exactly like a
                # subset lint — the tier's own full run audits it
                continue
            if SUPPRESSION_AUDIT_RULE in rules:
                # an opt-out marker is "used" exactly when it waives a
                # dormant neighbor (recorded below). A PURE marker that
                # ends up waiving nothing is itself rot and is audited
                # after all neighbors have been processed; a combined
                # line (ok(<rule>, unused-suppression)) self-waives.
                if rules == {SUPPRESSION_AUDIT_RULE}:
                    markers.append(line)
                continue
            unknown = sorted(
                r for r in rules
                if r not in known_rules and r != "all")
            waiver = mod.match_suppression(SUPPRESSION_AUDIT_RULE, line)
            if waiver is not None:
                used_lines.add(waiver)
                continue
            if unknown:
                msg = (f"suppression names unknown rule(s) "
                       f"{', '.join(unknown)} — a typo'd ok() suppresses "
                       "nothing; fix the rule name or delete the comment")
            else:
                msg = (f"stale suppression: ok({', '.join(sorted(rules))}) "
                       "no longer suppresses any finding — delete it, or "
                       "waive deliberately-dormant waivers with "
                       "ok(unused-suppression)")
            out.append(Finding(SUPPRESSION_AUDIT_RULE, mod.relpath,
                               line, 0, msg))
        for line in markers:
            if line in used_lines:
                continue
            out.append(Finding(
                SUPPRESSION_AUDIT_RULE, mod.relpath, line, 0,
                "orphaned ok(unused-suppression): it waives no dormant "
                "suppression beside it — the waiver it covered was "
                "deleted; delete this marker too"))
    return out


def lint(model: RepoModel,
         ir_findings: Optional[List[Finding]] = None,
         ast_checks: bool = True) -> List[Finding]:
    """Run the AST checkers (plus, when ``ir_findings`` is given, merge the
    IR tier's pre-suppression findings) through the one suppression and
    rot-audit pipeline. ``ir_findings=None`` means the IR tier did not run:
    its rules stay *known* (a typo'd ok(ir-dtype) is still flagged) but
    *dormant* for staleness — only a run that actually traced the registry
    can decide whether an IR suppression is live. ``ast_checks=False``
    (the ``--ir-only`` path) skips the AST checkers; pair it with a
    subset model so the rot audit — undecidable without them — stays off."""
    from tools.graftlint import checks

    findings: List[Finding] = []
    by_path = {m.relpath: m for m in model.modules}
    used: Dict[int, Set[int]] = defaultdict(set)  # id(mod) -> comment lines

    def _consume(stream):
        for f in stream:
            mod = by_path.get(f.path)
            if mod is not None:
                sline = mod.match_suppression(f.rule, f.line)
                if sline is not None:
                    used[id(mod)].add(sline)
                    continue
            findings.append(f)

    if ast_checks:
        for checker in checks.ALL:
            _consume(checker.check(model))
    if ir_findings is not None:
        _consume(ir_findings)
    if not model.subset:
        # the rot audit is only decidable against the full package: a
        # suppression whose finding resolves through modules OUTSIDE the
        # linted subset (a locked device launch into an unlinted jitted
        # callee, say) would look stale on every partial lint
        known = set(checks.RULES) | {SUPPRESSION_AUDIT_RULE} | set(IR_RULES)
        dormant = IR_RULES if ir_findings is None else frozenset()
        findings += _audit_suppressions(model, used, known, dormant)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_paths(paths: Iterable[str], subset: bool = False,
               ir_findings: Optional[List[Finding]] = None) -> List[Finding]:
    """Lint ``paths``. ``subset=True`` marks a partial lint (the
    ``--changed`` precommit fast path): cross-artifact rules that are
    only decidable against the full package — the suppression-rot audit
    and env-knob-drift's doc cross-check — gate themselves off; CI's
    full lint keeps them on. ``ir_findings`` merges the IR tier's
    pre-suppression findings (``tools.graftlint.ir.lint_ir()``) into the
    same suppression/audit pipeline."""
    return lint(build_model(paths, subset=subset), ir_findings=ir_findings)
