"""frame-protocol: wire-protocol exhaustiveness + pinned-lock-map audit.

The RPC frame protocol (parallel/rpc.py) is a closed-world contract:
every ``KIND_*`` constant a peer can put on the wire must be decoded by
the other side, or the receiver tears the connection down at runtime on
"unexpected frame kind" — in production, against a live peer. This
checker proves the contract statically, per protocol module (a module
named ``rpc.py`` defining ``KIND_*`` constants, paired with the
``server.py`` in the same directory):

- **kind uniqueness** — two kinds sharing a wire value desync every
  dispatch table;
- **mux registration** — every ``KIND_*_MUX`` tagged kind must be a
  value of ``MUX_RESPONSE_KINDS`` (the demux unwraps via its inverse,
  ``_MUX_TO_BASE``; an unregistered tagged kind is undecodable);
- **exhaustive dispatch** — a kind the server produces (referenced in
  the server module outside its ``_one_call`` dispatcher, plus the mux
  variant of every base kind where the server writes tagged responses)
  must be consumed by the client (``Client._interpret`` or the
  ``_reader_loop`` demux); a kind the client produces (referenced in the
  client class outside those consumers) must be consumed by
  ``_one_call``;
- **payload arity** — the ``KIND_CALL`` tuple literal at client pack
  sites must satisfy the server's unpack of the decoded payload (an
  unguarded ``a, b, c = payload`` against a 4-element frame is a
  ValueError on every call; ``payload[:3]`` must not slice more than the
  smallest pack site provides);
- **meta-key contract** — the CALL frame's optional trailing meta dict
  is the extensible half of the protocol (``req_id``, ``deadline_s``,
  the tracing ``trace_id``): every key the client class stores into its
  ``meta`` dict (literal or ``meta["k"] = ...``) must be read by the
  paired server's ``_one_call`` (a ``.get("k")``) — an unread key is
  wire surface the in-repo server silently drops, i.e. a feature that
  only APPEARS to propagate. (Old peers ignoring unknown keys is the
  compat contract; the in-repo pair agreeing is this checker's.)
- **dead kinds** — a kind defined but never referenced again is wiring
  someone forgot to finish;
- **binary-wire contract** (ISSUE 14; each rule gates on its marker, so
  pre-wire protocol modules and fixtures stay quiet): (a) no ``KIND_*``
  wire value may collide with the ``WIRE_BINARY_FLAG`` kind-byte bit —
  a flagged frame would decode as a DIFFERENT kind on a peer; (b) every
  op advertised in ``BINARY_CALL_OPS`` (the binary CALL schema registry,
  in the protocol module or its sibling ``wire.py``) must be a public
  method the paired server actually serves — an encodable op the
  dispatch cannot serve is dead wire surface; (c) ``restricted_loads``
  is pinned as the ONLY pickle decode entry point: any
  ``pickle.loads`` / ``pickle.load`` / ``pickle.Unpickler`` reference in
  the protocol module outside ``restricted_loads`` /
  ``_RestrictedUnpickler`` is a finding (the binary path must never grow
  a second unpickler, and neither may anything else);
- **stale pins** — every entry of the lock-discipline ``PINS`` map
  (checks/locks.py, the reviewed allowlist) must resolve: the named
  class exists, the attribute is actually assigned in it, and the lock
  is a real lock attribute of that class. A pin that stops resolving is
  a checker silently switched off — the drift this rule exists to fail
  CI on. (Audited for PR 7: every PR 3-6 hand-pinned entry currently
  resolves.) Runs only when the linted set contains the real package
  (engine.py + parallel/rpc.py), so fixture lints stay quiet.
"""

import ast
import os
import re
from collections import defaultdict

from tools.graftlint.core import Finding, lock_attrs

RULE = "frame-protocol"

_KIND_RE = re.compile(r"^KIND_[A-Z0-9_]+$")
_PACK_KIND_ARG = {"pack_frame": 0, "send_frame": 1, "pack_tagged_response": 0}


def _kind_ref(node, kinds):
    """Kind name when ``node`` references one (bare Name or ``mod.KIND_X``
    attribute), else None."""
    if isinstance(node, ast.Name) and node.id in kinds:
        return node.id
    if isinstance(node, ast.Attribute) and node.attr in kinds:
        return node.attr
    return None


def _collect_kinds(mod):
    """Module-level ``KIND_X = <int>`` constants: {name: (value, line)}."""
    kinds = {}
    dups = []
    for stmt in mod.tree.body:
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1):
            continue
        t = stmt.targets[0]
        if not (isinstance(t, ast.Name) and _KIND_RE.match(t.id)):
            continue
        if not (isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, int)):
            continue
        val = stmt.value.value
        for name, (v, _ln) in kinds.items():
            if v == val:
                dups.append((t.id, name, val, stmt.lineno))
        kinds[t.id] = (val, stmt.lineno)
    return kinds, dups


def _mux_map(mod, kinds):
    """{base kind name: mux kind name} from the module-level
    ``MUX_RESPONSE_KINDS`` dict literal, or None when absent."""
    for stmt in mod.tree.body:
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1):
            continue
        t = stmt.targets[0]
        if not (isinstance(t, ast.Name) and t.id == "MUX_RESPONSE_KINDS"):
            continue
        if not isinstance(stmt.value, ast.Dict):
            return None
        out = {}
        for k, v in zip(stmt.value.keys, stmt.value.values):
            kn, vn = _kind_ref(k, kinds), _kind_ref(v, kinds)
            if kn and vn:
                out[kn] = vn
        return out
    return None


def _refs_in(node, kinds):
    """(kind name, line) for every kind reference under ``node``."""
    for sub in ast.walk(node):
        name = _kind_ref(sub, kinds)
        if name is not None and not (
                isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store)):
            yield name, sub.lineno


def _functions_named(mod, name):
    return [f for f in mod.functions if f.name == name]


def check(model):
    yield from _check_protocols(model)
    yield from _check_pins(model)


# ------------------------------------------------------------------ protocol

def _check_protocols(model):
    servers_by_dir = {}
    for mod in model.modules:
        if os.path.basename(mod.relpath) == "server.py":
            servers_by_dir[os.path.dirname(mod.relpath)] = mod

    for mod in model.modules:
        if os.path.basename(mod.relpath) != "rpc.py":
            continue
        kinds, dups = _collect_kinds(mod)
        if not kinds:
            continue
        for dup_name, first_name, val, line in dups:
            yield Finding(
                RULE, mod.relpath, line, 0,
                f"frame kind {dup_name} reuses wire value {val} already "
                f"taken by {first_name} — kinds must be unique",
            )

        yield from _check_wire_flag(mod, kinds)
        yield from _check_pickle_entry(mod)

        mux = _mux_map(mod, kinds)
        mux_values = set(mux.values()) if mux else set()
        mux_reported = set()
        for name, (_val, line) in sorted(kinds.items()):
            if name.endswith("_MUX") and name not in mux_values:
                mux_reported.add(name)
                yield Finding(
                    RULE, mod.relpath, line, 0,
                    f"tagged kind {name} is not registered in "
                    "MUX_RESPONSE_KINDS — the demux reader cannot unwrap "
                    "it (_MUX_TO_BASE is its inverse)",
                )

        # --- locate the client class and its consumer methods ----------
        client_cls = None
        for cnode in mod.classes:
            if any(isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
                   and s.name == "_interpret" for s in cnode.body):
                client_cls = cnode
                break
        client_consumed = set()
        client_produced = {}  # kind -> first producing line
        demux_unwraps_mux = False
        if client_cls is not None:
            for sub in client_cls.body:
                if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                refs = list(_refs_in(sub, kinds))
                if sub.name in ("_interpret", "_reader_loop"):
                    client_consumed |= {n for n, _ln in refs}
                    if sub.name == "_reader_loop":
                        for n2 in ast.walk(sub):
                            if (isinstance(n2, ast.Name) and n2.id in
                                    ("_MUX_TO_BASE", "MUX_RESPONSE_KINDS")):
                                demux_unwraps_mux = True
                else:
                    for n, ln in refs:
                        client_produced.setdefault(n, ln)
        if demux_unwraps_mux:
            client_consumed |= mux_values

        # --- the paired server module ----------------------------------
        server = servers_by_dir.get(os.path.dirname(mod.relpath))
        server_consumed = set()
        server_produced = {}
        server_writes_tagged = False
        if server is not None:
            one_call = _functions_named(server, "_one_call")
            for f in one_call:
                server_consumed |= {n for n, _ln in _refs_in(f.node, kinds)}
            one_call_ids = {id(f.node) for f in one_call}
            for f in server.functions:
                if id(f.node) in one_call_ids:
                    continue
                for n, ln in _refs_in(f.node, kinds):
                    server_produced.setdefault(n, ln)
                for sub in ast.walk(f.node):
                    if (isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "pack_tagged_response"):
                        server_writes_tagged = True
            if server_writes_tagged and mux:
                for base, ln in list(server_produced.items()):
                    if base in mux:
                        server_produced.setdefault(mux[base], ln)

            for name in sorted(server_produced):
                if name in server_consumed:
                    continue  # also dispatched server-side (e.g. CALL echo)
                if name not in client_consumed:
                    yield Finding(
                        RULE, server.relpath, server_produced[name], 0,
                        f"server sends {name} but the client never handles "
                        "it (neither _interpret nor the demux reader) — "
                        "the connection dies with 'unexpected frame kind' "
                        "at runtime",
                    )
            for name in sorted(client_produced):
                if name in client_consumed:
                    continue
                if name not in server_consumed:
                    yield Finding(
                        RULE, mod.relpath, client_produced[name], 0,
                        f"client sends {name} but the server's _one_call "
                        "dispatcher never handles it",
                    )

            yield from _check_call_arity(mod, server, kinds, client_cls)
            yield from _check_call_meta(mod, server, client_cls)
            yield from _check_binary_ops(model, mod, server)

        # --- dead kinds -------------------------------------------------
        referenced = set()
        for m in (mod, server) if server is not None else (mod,):
            for stmt in m.tree.body:
                # definition sites never appear here: _refs_in already
                # excludes Store-context names, so every hit is a load
                for n, _ln in _refs_in(stmt, kinds):
                    referenced.add(n)
        for name, (_val, line) in sorted(kinds.items()):
            if name not in referenced and name not in mux_reported:
                yield Finding(
                    RULE, mod.relpath, line, 0,
                    f"frame kind {name} is defined but never sent, "
                    "dispatched, or registered — dead protocol surface",
                )


def _module_int_const(mod, name):
    """(value, line) of a module-level ``NAME = <int literal>``, or None."""
    for stmt in mod.tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == name
                and isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, int)):
            return stmt.value.value, stmt.lineno
    return None


def _check_wire_flag(mod, kinds):
    """Binary-wire rule (a): no KIND_* value may carry the
    WIRE_BINARY_FLAG bit — ``recv`` strips the flag before dispatch, so
    a colliding kind's frames would decode as a DIFFERENT kind. Gated on
    the module defining the flag (pre-wire protocols stay quiet)."""
    flag = _module_int_const(mod, "WIRE_BINARY_FLAG")
    if flag is None:
        return
    flag_value, _flag_line = flag
    for name, (val, line) in sorted(kinds.items()):
        if val & flag_value:
            yield Finding(
                RULE, mod.relpath, line, 0,
                f"frame kind {name} wire value {val:#x} collides with the "
                f"binary-skeleton flag bit WIRE_BINARY_FLAG "
                f"({flag_value:#x}) — its flagged frames would decode as "
                "a different kind",
            )


def _check_pickle_entry(mod):
    """Binary-wire rule (c): ``restricted_loads`` is the ONLY pickle
    decode entry point in the protocol module. Gated on the module
    defining ``restricted_loads`` (fixture protocols without the pickle
    machinery stay quiet). ``pickle.dumps`` (the encode side) and
    ``pickle.UnpicklingError`` (exception classification) stay legal
    everywhere."""
    allowed = []
    for stmt in mod.tree.body:
        if (isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                and stmt.name == "restricted_loads"):
            allowed.append(stmt)
        elif (isinstance(stmt, ast.ClassDef)
                and stmt.name == "_RestrictedUnpickler"):
            allowed.append(stmt)
    if not any(isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
               for n in allowed):
        return
    allowed_lines = set()
    for n in allowed:
        for sub in ast.walk(n):
            if hasattr(sub, "lineno"):
                allowed_lines.add(sub.lineno)
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "pickle"
                and node.attr in ("loads", "load", "Unpickler")
                and node.lineno not in allowed_lines):
            yield Finding(
                RULE, mod.relpath, node.lineno, 0,
                f"pickle.{node.attr} outside restricted_loads/"
                "_RestrictedUnpickler — restricted_loads is pinned as the "
                "ONLY pickle decode entry point for wire bytes",
            )


def _check_binary_ops(model, mod, server):
    """Binary-wire rule (b): every op in ``BINARY_CALL_OPS`` (the binary
    CALL schema registry — in the protocol module or its sibling
    ``wire.py``) must be a public function the paired server defines:
    the binary-encodable op set and the server's decode dispatch must
    stay closed over each other."""
    mod_dir = os.path.dirname(mod.relpath)
    candidates = [mod]
    for m in model.modules:
        if (os.path.dirname(m.relpath) == mod_dir
                and os.path.basename(m.relpath) == "wire.py"):
            candidates.append(m)
    ops_home, ops, ops_line = None, None, 0
    for m in candidates:
        for stmt in m.tree.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "BINARY_CALL_OPS"
                    and isinstance(stmt.value, (ast.Tuple, ast.List))):
                vals = [e.value for e in stmt.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)]
                ops_home, ops, ops_line = m, vals, stmt.lineno
                break
        if ops is not None:
            break
    if not ops:
        return
    served = {f.name for f in server.functions}
    for op in ops:
        if op.startswith("_") or op not in served:
            yield Finding(
                RULE, ops_home.relpath, ops_line, 0,
                f"binary-encodable op {op!r} (BINARY_CALL_OPS) is not a "
                "public function of the paired server — the binary CALL "
                "schema advertises an op the decode dispatch cannot serve",
            )


def _check_call_arity(mod, server, kinds, client_cls):
    """KIND_CALL pack-site tuple arities vs the server's payload unpack."""
    if "KIND_CALL" not in kinds:
        return
    arities = {}  # arity -> line (first site)
    scope = client_cls if client_cls is not None else mod.tree
    for sub in ast.walk(scope):
        if not isinstance(sub, ast.Call):
            continue
        fn = sub.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        pos = _PACK_KIND_ARG.get(name)
        if pos is None or len(sub.args) <= pos:
            continue
        if _kind_ref(sub.args[pos], kinds) != "KIND_CALL":
            continue
        if len(sub.args) > pos + 1 and isinstance(sub.args[pos + 1], ast.Tuple):
            arity = len(sub.args[pos + 1].elts)
            arities.setdefault(arity, sub.lineno)
    if not arities:
        return
    lo = min(arities)
    for f in _functions_named(server, "_one_call"):
        payload_var = None
        for sub in ast.walk(f.node):
            if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1):
                continue
            t, v = sub.targets[0], sub.value
            if (payload_var is None and isinstance(t, ast.Tuple)
                    and len(t.elts) == 2
                    and isinstance(v, ast.Call)
                    and ((isinstance(v.func, ast.Attribute)
                          and v.func.attr == "recv_frame")
                         or (isinstance(v.func, ast.Name)
                             and v.func.id == "recv_frame"))
                    and isinstance(t.elts[1], ast.Name)):
                payload_var = t.elts[1].id
                continue
            if payload_var is None or not isinstance(t, ast.Tuple):
                continue
            n_targets = len(t.elts)
            if isinstance(v, ast.Name) and v.id == payload_var:
                if any(a != n_targets for a in arities):
                    bad = sorted(a for a in arities if a != n_targets)
                    yield Finding(
                        RULE, server.relpath, sub.lineno, 0,
                        f"_one_call unpacks exactly {n_targets} elements "
                        f"from the KIND_CALL payload, but a client pack "
                        f"site sends {bad[0]} "
                        f"({mod.relpath}:{arities[bad[0]]}) — slice the "
                        "payload to stay wire-compatible",
                    )
            elif (isinstance(v, ast.Subscript)
                    and isinstance(v.value, ast.Name)
                    and v.value.id == payload_var
                    and isinstance(v.slice, ast.Slice)
                    and v.slice.lower is None
                    and isinstance(v.slice.upper, ast.Constant)):
                n_slice = v.slice.upper.value
                if n_slice > lo:
                    yield Finding(
                        RULE, server.relpath, sub.lineno, 0,
                        f"_one_call slices {n_slice} elements from the "
                        f"KIND_CALL payload, but a client pack site sends "
                        f"only {lo} ({mod.relpath}:{arities[lo]})",
                    )


def _check_call_meta(mod, server, client_cls):
    """CALL-frame meta contract: every key the client stores into a
    ``meta`` dict (the optional trailing element of a KIND_CALL payload)
    must be consumed by the paired server's ``_one_call`` via
    ``.get("<key>")``. Conventions this resolves: dict literals assigned
    to a variable named ``meta`` and constant-string subscript stores
    into one (the two shapes the in-repo client uses)."""
    if client_cls is None:
        return
    sent = {}  # key -> first client line that sets it
    for sub in ast.walk(client_cls):
        if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1):
            continue
        t = sub.targets[0]
        if (isinstance(t, ast.Name) and t.id == "meta"
                and isinstance(sub.value, ast.Dict)):
            for k in sub.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    sent.setdefault(k.value, sub.lineno)
        elif (isinstance(t, ast.Subscript)
                and isinstance(t.value, ast.Name) and t.value.id == "meta"
                and isinstance(t.slice, ast.Constant)
                and isinstance(t.slice.value, str)):
            sent.setdefault(t.slice.value, sub.lineno)
    if not sent:
        return
    consumed = set()
    for f in _functions_named(server, "_one_call"):
        for sub in ast.walk(f.node):
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "get" and sub.args
                    and isinstance(sub.args[0], ast.Constant)
                    and isinstance(sub.args[0].value, str)):
                consumed.add(sub.args[0].value)
    for key in sorted(sent):
        if key not in consumed:
            yield Finding(
                RULE, mod.relpath, sent[key], 0,
                f"client CALL frames carry meta key {key!r} that the "
                "paired server's _one_call never reads — the key is dead "
                "on the wire against in-repo peers (read it with "
                f"frame_meta.get({key!r}) or stop sending it)",
            )


# ------------------------------------------------------------------ pin audit

def _check_pins(model):
    """Every PINS entry must resolve against the linted classes; only
    meaningful when every pinned class's home module is in the model
    (fixture lints and `--changed` subsets skip — an absent class in a
    partial lint is not a stale pin)."""
    from tools.graftlint.checks import locks as locks_mod

    present = {m.relpath for m in model.modules if "fixtures" not in m.relpath}
    for home in locks_mod.PIN_HOMES:
        if not any(rel.endswith(home) for rel in present):
            return

    pins_path = os.path.relpath(locks_mod.__file__).replace(os.sep, "/")
    try:
        with open(locks_mod.__file__, "r", encoding="utf-8") as f:
            pins_lines = f.read().splitlines()
    except OSError:  # pragma: no cover - the module was importable
        pins_lines = []

    def pin_line(cls, attr):
        needle = f'("{cls}", "{attr}")'
        for i, text in enumerate(pins_lines, 1):
            if needle in text:
                return i
        return 1

    classes = defaultdict(list)
    for mod in model.modules:
        if "fixtures" in mod.relpath:
            continue
        for node in mod.classes:
            classes[node.name].append(node)

    for (cls, attr), lock in sorted(locks_mod.PINS.items()):
        nodes = classes.get(cls)
        if not nodes:
            yield Finding(
                RULE, pins_path, pin_line(cls, attr), 0,
                f"stale pin: class {cls} (pinned attr `{attr}` under "
                f"`{lock}`) does not exist in the linted package — remove "
                "or correct the PINS entry",
            )
            continue
        attr_ok = any(_assigns_self_attr(n, attr) for n in nodes)
        lock_ok = any(lock in lock_attrs(n) for n in nodes)
        if not attr_ok:
            yield Finding(
                RULE, pins_path, pin_line(cls, attr), 0,
                f"stale pin: {cls}.{attr} is never assigned in class "
                f"{cls} — the lock-discipline pin no longer guards "
                "anything",
            )
        if not lock_ok:
            yield Finding(
                RULE, pins_path, pin_line(cls, attr), 0,
                f"stale pin: {cls}.{lock} is not a lock attribute of "
                f"{cls} (neither a threading primitive nor a lockdep "
                "factory) — the pinned guard cannot be enforced",
            )


def _assigns_self_attr(class_node, attr):
    for node in ast.walk(class_node):
        if isinstance(node, ast.Attribute) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            if (isinstance(node.value, ast.Name) and node.value.id == "self"
                    and node.attr == attr):
                return True
    return False
