"""host-sync: no silent host-device synchronization on the serving hot path.

Hot-path functions are the call-graph closure of ``Index.search``
(core.HOT_ROOTS) plus anything annotated ``# graftlint: hot``. Inside them:

- ``.item()`` is always a blocking device->host transfer.
- ``jax.device_get(...)`` likewise.
- ``np.asarray``/``np.array``/``np.ascontiguousarray`` whose argument
  expression visibly contains a ``jnp.*`` expression or a call to a
  known-jitted function materializes a device array on the host.
- ``float()``/``int()``/``bool()`` coercions whose argument contains a
  reduction method call (``.max()``, ``.any()``, ...) on a non-numpy root,
  a ``jnp.*`` expression, or a known-jitted call: the coercion forces the
  value to the host (and for reductions, scans the array on the serving
  thread even when it is already host-side).

Precision-first: a device array hiding in a bare local name is invisible
to this checker; the conventions doc (docs/LINTING.md) asks hot-path code
to keep its one designed device fetch per block behind an obvious
``np.asarray(<jitted call>)`` or to annotate with ``# graftlint: ok``.
"""

import ast

from tools.graftlint.core import (
    Finding, NUMPY_ALIASES, attr_root, call_name, dotted,
)

RULE = "host-sync"

_REDUCTIONS = frozenset({
    "item", "max", "min", "sum", "any", "all", "argmax", "argmin", "mean",
})
_HOST_CASTS = frozenset({"float", "int", "bool"})
_NP_MATERIALIZERS = frozenset({"asarray", "array", "ascontiguousarray"})


def _mentions_device(node: ast.AST, jitted_names) -> bool:
    """Does this expression visibly produce a device value: a ``jnp.*``
    attribute chain or a call to a known-jitted function?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and attr_root(sub) == "jnp":
            return True
        if isinstance(sub, ast.Call):
            n = call_name(sub)
            if n in jitted_names:
                return True
    return False


def _reduction_on_array(node: ast.AST) -> bool:
    """A ``.max()``-style reduction method call whose root is not a numpy
    module alias (``np.max(...)`` is an explicit host-side formulation)."""
    for sub in ast.walk(node):
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in _REDUCTIONS
                and attr_root(sub.func) not in NUMPY_ALIASES):
            return True
    return False


def check(model):
    jitted = model.jitted_names
    for fi in model.functions:
        if not fi.hot:
            continue
        mod = fi.module
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item" and not node.args):
                yield Finding(
                    RULE, mod.relpath, node.lineno, node.col_offset,
                    f"`.item()` in hot-path function {fi.qualname} blocks on "
                    "a device->host transfer",
                )
            elif d == "jax.device_get":
                yield Finding(
                    RULE, mod.relpath, node.lineno, node.col_offset,
                    f"`jax.device_get` in hot-path function {fi.qualname}",
                )
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _NP_MATERIALIZERS
                    and attr_root(node.func) in NUMPY_ALIASES
                    and node.args
                    and _mentions_device(node.args[0], jitted)):
                yield Finding(
                    RULE, mod.relpath, node.lineno, node.col_offset,
                    f"`np.{node.func.attr}` over a device expression in "
                    f"hot-path function {fi.qualname} forces a host sync",
                )
            elif (isinstance(node.func, ast.Name)
                    and node.func.id in _HOST_CASTS
                    and len(node.args) == 1
                    and (_mentions_device(node.args[0], jitted)
                         or _reduction_on_array(node.args[0]))):
                yield Finding(
                    RULE, mod.relpath, node.lineno, node.col_offset,
                    f"`{node.func.id}(...)` coercion over an array reduction "
                    f"in hot-path function {fi.qualname}; hoist to an "
                    "explicit np.* host op or fetch once per block",
                )
