"""thread-lifecycle: every thread is named, daemon-explicit, tracked, and
join-reachable from a lifecycle method.

Three thread-heavy subsystems (scheduler, replication, anti-entropy /
compaction) rest on a hand-maintained convention: a thread you cannot
name in a stack dump, cannot find in a tracked attribute, or cannot join
from ``stop()``/``close()``/``retire()`` is a thread that leaks past
shutdown — exactly the failure the DFT_THREADCHECK=1 runtime witness
(utils/threadcheck.py) catches per test, and this checker proves the
preconditions for statically. For every ``threading.Thread(...)``
creation site:

- **named** — a ``name=`` keyword is required ("Thread-7" in a deadlocked
  stack dump attributes to nothing);
- **daemon-explicit** — a ``daemon=`` keyword is required: daemonness is
  the lifecycle contract (daemon = event/connection-bound lifetime,
  non-daemon = join-bound), so it must be a reviewed decision, not an
  inherited default;
- **tracked** — the Thread object must be registered somewhere an owner
  can reach: assigned to a ``self.`` attribute, appended/added to a
  container, returned, or handed to another call. A chained
  ``threading.Thread(...).start()`` (or a started local nobody stores)
  is an orphan;
- **join-reachable** — a thread tracked in ``self.<attr>`` must have a
  ``.join(...)`` on that attribute reachable from one of the class's
  lifecycle methods (``stop``/``close``/``retire``/``shutdown``/
  ``join``/``__exit__``/``__del__``), walking call edges the
  precision-first way (``self.method()`` dispatch, same-module bare
  names — the lock-order resolver), so a join hidden in a helper still
  counts and a join nothing can reach does not. Snapshot-then-join
  patterns (``t = self._thread; t.join(...)``, ``for t in
  self._threads: t.join(...)``, ``ts = list(self._threads)``) resolve
  through one level of local aliasing.

``_thread.start_new_thread`` is always a finding: the raw spawn is
invisible to shutdown, to stack-dump naming, and to the runtime witness.

Deliberate fire-and-forget sites (per-connection reader threads whose
lifetime IS the connection's) carry
``# graftlint: ok(thread-lifecycle): <reason>``.
"""

import ast
from collections import defaultdict

from tools.graftlint.core import Finding, dotted

RULE = "thread-lifecycle"

# lifecycle methods a join path must be reachable from
LIFECYCLE = frozenset({
    "stop", "close", "retire", "shutdown", "join", "__exit__", "__del__",
})

_TRACK_METHODS = frozenset({"append", "add", "insert"})


def _is_thread_ctor(call: ast.Call, mod) -> bool:
    d = dotted(call.func)
    if d == "threading.Thread":
        return True
    if isinstance(call.func, ast.Name):
        return mod.import_aliases.get(call.func.id) == "threading.Thread"
    return False


def _kwarg(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name:
            return kw
    return None


def _parent_map(root):
    parents = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _self_attr_of(node):
    """'attr' for ``self.attr`` expressions, else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _self_attrs_in(expr):
    """Every self.<attr> name appearing anywhere under ``expr``."""
    out = set()
    for sub in ast.walk(expr):
        a = _self_attr_of(sub)
        if a:
            out.add(a)
    return out


def _tracking_of(ctor, parents, fi):
    """How a Thread ctor's value is retained, as ``(kind, attr)``:

    - ("attr", X)      — lands in ``self.X`` (directly or via a local)
    - ("container", X) — appended/added to ``self.X`` (or a local)
    - ("escapes", None)— returned / passed to another call: tracked by
                         the receiver, join checked there (if at all)
    - (None, None)     — started and dropped: an orphan
    """
    p = parents.get(ctor)
    # chained `threading.Thread(...).start()`
    if isinstance(p, ast.Attribute) and isinstance(parents.get(p), ast.Call):
        return (None, None)
    if isinstance(p, ast.Assign):
        for t in p.targets:
            attr = _self_attr_of(t)
            if attr:
                return ("attr", attr)
        # local: scan the whole enclosing function for where it goes
        local = next((t.id for t in p.targets if isinstance(t, ast.Name)),
                     None)
        if local is None:
            return ("escapes", None)
        for sub in ast.walk(fi.node):
            if isinstance(sub, ast.Assign):
                if any(isinstance(v, ast.Name) and v.id == local
                       for v in ast.walk(sub.value)):
                    for t in sub.targets:
                        attr = _self_attr_of(t)
                        if attr:
                            return ("attr", attr)
            if isinstance(sub, ast.Call):
                uses_local = any(
                    isinstance(a, ast.Name) and a.id == local
                    for a in list(sub.args)
                    + [kw.value for kw in sub.keywords])
                if not uses_local:
                    continue
                f = sub.func
                if (isinstance(f, ast.Attribute)
                        and f.attr in _TRACK_METHODS):
                    attr = _self_attr_of(f.value)
                    return ("container", attr)  # attr may be None (local)
                if not (isinstance(f, ast.Attribute)
                        and f.attr == "start"):
                    return ("escapes", None)
            if (isinstance(sub, ast.Return) and sub.value is not None
                    and any(isinstance(v, ast.Name) and v.id == local
                            for v in ast.walk(sub.value))):
                return ("escapes", None)
        return (None, None)
    if isinstance(p, ast.Call) and ctor in p.args:
        return ("escapes", None)
    if isinstance(p, ast.keyword):
        return ("escapes", None)
    if isinstance(p, ast.Return):
        return ("escapes", None)
    return (None, None)


# ----------------------------------------------------- join reachability

def _class_methods(model):
    """(id(module), class name) -> {method name: FunctionInfo}."""
    out = defaultdict(dict)
    for fi in model.functions:
        if fi.cls is not None:
            out[(id(fi.module), fi.cls)][fi.name] = fi
    return out


def _joined_attrs(methods):
    """Self attributes with ``.join(...)`` evidence in methods reachable
    from a lifecycle method via ``self.method()`` / same-class bare-name
    edges — the taint propagation that attributes a join in a helper to
    the lifecycle path that reaches it."""
    # reachability over the class's own methods
    reachable = {n for n in methods if n in LIFECYCLE}
    frontier = list(reachable)
    while frontier:
        m = methods[frontier.pop()]
        for sub in ast.walk(m.node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            callee = None
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self" and f.attr in methods):
                callee = f.attr
            elif isinstance(f, ast.Name) and f.id in methods:
                callee = f.id
            if callee is not None and callee not in reachable:
                reachable.add(callee)
                frontier.append(callee)

    joined = set()
    for name in reachable:
        node = methods[name].node
        # one level of local aliasing: v = self.X / ts = list(self.X) /
        # for v in self.X — each maps the local to the attrs it came
        # from. Iterated to a fixpoint: ast.walk is breadth-first, so a
        # snapshot assignment nested in a `with` block is visited AFTER
        # the top-level for-loop that consumes it
        alias = defaultdict(set)
        for _ in range(3):
            grew = False
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    # element-wise tuple unpack (`ts, self.X = self.X, []`
                    # — the snapshot-and-swap drain idiom) before the
                    # whole-RHS fallback
                    pairs = []
                    for t in sub.targets:
                        if (isinstance(t, ast.Tuple)
                                and isinstance(sub.value, ast.Tuple)
                                and len(t.elts) == len(sub.value.elts)):
                            pairs += list(zip(t.elts, sub.value.elts))
                        else:
                            pairs.append((t, sub.value))
                    for tgt, val in pairs:
                        if not isinstance(tgt, ast.Name):
                            continue
                        attrs = set(_self_attrs_in(val))
                        for n in ast.walk(val):
                            if (isinstance(n, ast.Name)
                                    and isinstance(n.ctx, ast.Load)):
                                attrs |= alias.get(n.id, set())
                        if not attrs <= alias[tgt.id]:
                            alias[tgt.id] |= attrs
                            grew = True
                elif isinstance(sub, (ast.For, ast.AsyncFor)):
                    srcs = set(_self_attrs_in(sub.iter))
                    for n in ast.walk(sub.iter):
                        if (isinstance(n, ast.Name)
                                and isinstance(n.ctx, ast.Load)):
                            srcs |= alias.get(n.id, set())
                    if (isinstance(sub.target, ast.Name)
                            and not srcs <= alias[sub.target.id]):
                        alias[sub.target.id] |= srcs
                        grew = True
            if not grew:
                break
        for sub in ast.walk(node):
            if not (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "join"):
                continue
            base = sub.func.value
            attr = _self_attr_of(base)
            if attr:
                joined.add(attr)
            elif isinstance(base, ast.Name):
                joined |= alias.get(base.id, set())
    return joined


def check(model):
    methods_by_cls = _class_methods(model)
    joined_cache = {}

    def joined_attrs_for(key):
        if key not in joined_cache:
            joined_cache[key] = _joined_attrs(methods_by_cls.get(key, {}))
        return joined_cache[key]

    for fi in model.functions:
        mod = fi.module
        parents = None
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            if dotted(node.func) == "_thread.start_new_thread":
                yield Finding(
                    RULE, mod.relpath, node.lineno, node.col_offset,
                    f"{fi.qualname} spawns via _thread.start_new_thread — "
                    "unnamed, untracked, invisible to shutdown and the "
                    "DFT_THREADCHECK witness; use a named, tracked "
                    "threading.Thread",
                )
                continue
            if not _is_thread_ctor(node, mod):
                continue
            where = f"{fi.qualname} creates a thread"
            if _kwarg(node, "name") is None:
                yield Finding(
                    RULE, mod.relpath, node.lineno, node.col_offset,
                    f"{where} without name= — an anonymous 'Thread-N' in "
                    "a stack dump or leak report attributes to nothing",
                )
            if _kwarg(node, "daemon") is None:
                yield Finding(
                    RULE, mod.relpath, node.lineno, node.col_offset,
                    f"{where} without an explicit daemon= — daemonness is "
                    "the lifecycle contract (daemon: event/connection-"
                    "bound; non-daemon: join-bound) and must be a "
                    "reviewed decision",
                )
            if parents is None:
                parents = _parent_map(fi.node)
            kind, attr = _tracking_of(node, parents, fi)
            if kind is None:
                yield Finding(
                    RULE, mod.relpath, node.lineno, node.col_offset,
                    f"{where} that is started but never registered in a "
                    "tracked container (self attribute, list, or caller) "
                    "— an orphan no stop()/close()/retire() can reach",
                )
                continue
            if attr is None:
                continue  # escapes / local container: join checked elsewhere
            if fi.cls is not None:
                keys = [(id(mod), fi.cls)]
            else:
                # helper spawn outside a class (module function storing
                # into a parameter's attribute): attribute the join
                # requirement to every linted class carrying that attr
                keys = [k for k, ms in methods_by_cls.items()
                        if any(attr in _self_attrs_in(m.node)
                               for m in ms.values())]
            if any(attr in joined_attrs_for(k) for k in keys):
                continue
            yield Finding(
                RULE, mod.relpath, node.lineno, node.col_offset,
                f"{where} tracked in `self.{attr}` with no .join() on it "
                "reachable from a lifecycle method "
                "(stop/close/retire/shutdown/join/__exit__/__del__) — "
                "tracked but unjoinable is still a leak",
            )
