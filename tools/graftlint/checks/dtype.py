"""dtype-discipline: accumulation dtype must be explicit in device matmuls.

Inside device-code modules (``ops/``, ``models/``, ``parallel/mesh.py``),
every matmul-class call — ``jnp.einsum``, ``jnp.dot``, ``jnp.matmul``,
``jnp.tensordot``, ``jax.lax.dot_general`` — must pass
``preferred_element_type``. Without it the accumulation dtype follows the
operand dtype: a bf16 operand silently accumulates in bf16 (precision
collapse on long contractions), and an f32 op that someone later feeds
bf16 storage inherits the collapse invisibly. Stating
``preferred_element_type=jnp.float32`` makes the contract explicit and is
a numerical no-op for f32 operands.

The ``@`` operator is deliberately out of scope (used only for tiny
host-shaped algebra like the OPQ procrustes rotation); the named APIs are
where list-scan and ADC accumulation lives.
"""

import ast

from tools.graftlint.core import Finding, attr_root, call_name

RULE = "dtype-discipline"

_MATMUL_NAMES = frozenset({"einsum", "dot", "matmul", "tensordot", "dot_general"})
_DEVICE_ROOTS = frozenset({"jnp", "jax", "lax"})


def _in_scope(mod) -> bool:
    p = mod.relpath
    return ("/ops/" in p or "/models/" in p or p.endswith("parallel/mesh.py")
            or p.startswith(("ops/", "models/")))


def check(model):
    for mod in model.modules:
        if not _in_scope(mod):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name not in _MATMUL_NAMES:
                continue
            root = attr_root(node.func) if isinstance(node.func, ast.Attribute) else None
            if root not in _DEVICE_ROOTS:
                continue  # np.dot etc: host numpy, accumulates in operand dtype by design
            if any(kw.arg == "preferred_element_type" for kw in node.keywords):
                continue
            yield Finding(
                RULE, mod.relpath, node.lineno, node.col_offset,
                f"`{root}...{name}` without preferred_element_type: "
                "accumulation dtype is implicit (bf16 operands would "
                "accumulate in bf16)",
            )
