"""lock-order: static lock-acquisition graph, deadlock-cycle findings.

Builds a directed graph over lock *classes* (``ClassName.lock_attr``
nodes) from three edge sources:

- nested ``with self.A: ... with self.B:`` blocks (edge A -> B);
- multi-item withs (``with self.A, self.B:`` acquires left to right);
- cross-function call edges: a call made while holding A, resolved
  name-based (bare names preferring same-module definitions, plus exact
  ``self.method()`` dispatch), contributes A -> L for every lock L the
  callee may TRANSITIVELY acquire.

Any cycle in that graph is a deadlock hazard: two threads walking the
cycle from different entry points can each hold one lock of the cycle
while waiting for the next. The finding carries the full acquisition
chain with the file:line where each edge is created, so the fix (pick
one global order) is mechanical.

Lexical model matches lock-discipline (checks/locks.py): lambdas inherit
the surrounding lock context, nested ``def``s reset it, and
``__init__``/``__new__``/``__del__`` are construction/teardown and
skipped. Precision-first like every graftlint checker: dynamic dispatch
(``getattr``, callbacks, function values) is invisible, so zero findings
is necessary, not sufficient — ``utils/lockdep.py`` (the DFT_LOCKDEP=1
runtime witness) covers the dynamic half of the same contract.
"""

import ast
from collections import defaultdict

from tools.graftlint.core import (
    Finding,
    HOT_EDGE_STOPLIST,
    lock_attrs,
    lock_context_events,
)

RULE = "lock-order"

_SKIP_METHODS = frozenset({"__init__", "__new__", "__del__"})


def _class_lock_names(model):
    """{(module, class_name): set of lock attrs} for every linted class,
    including locks pinned in the reviewed PINS map (so a lock spelled in
    a way `lock_attrs` cannot see still participates once pinned)."""
    from tools.graftlint.checks.locks import PINS

    pinned = defaultdict(set)
    for (cls, _attr), lock in PINS.items():
        pinned[cls].add(lock)
    out = {}
    for mod in model.modules:
        for node in mod.classes:
            names = lock_attrs(node) | pinned.get(node.name, set())
            if names:
                out[(mod, node.name)] = names
    return out


def _resolve(call, fi, model):
    """Callees a call site may reach, precision-first: bare names resolve
    to same-module functions (else a globally unique definition), and
    ``self.m()`` resolves exactly within the enclosing class. Everything
    else (attribute calls on other objects, function values) is dynamic
    dispatch and invisible by design."""
    f = call.func
    if isinstance(f, ast.Name):
        name = f.id
        if name in HOT_EDGE_STOPLIST:
            return []
        cands = model.by_name.get(name, [])
        same_mod = [g for g in cands if g.module is fi.module]
        if same_mod:
            return same_mod
        return list(cands) if len(cands) == 1 else []
    if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
            and f.value.id == "self" and fi.cls is not None):
        return [
            g for g in model.by_name.get(f.attr, ())
            if g.module is fi.module and g.cls == fi.cls
        ]
    return []


def check(model):
    class_locks = _class_lock_names(model)

    # per-function: direct lock acquisitions, call sites, and the
    # acquire/call events needed for edge provenance
    direct = {}       # id(fi) -> set of lock keys acquired in the body
    calls = {}        # id(fi) -> [(callee fi, line)]
    events = {}       # id(fi) -> [("acquire", key, held, line) | ("call", fi, held, line)]
    fns = {}          # id(fi) -> fi
    for fi in model.functions:
        if fi.cls is None or fi.name in _SKIP_METHODS:
            continue
        lock_names = class_locks.get((fi.module, fi.cls))
        if lock_names is None:
            continue
        key = lambda attr: f"{fi.cls}.{attr}"  # noqa: E731
        acq, csites, evs = set(), [], []
        for ev in lock_context_events(fi.node, lock_names):
            if ev[0] == "acquire":
                _, attr, held, node = ev
                acq.add(key(attr))
                evs.append(("acquire", key(attr),
                            tuple(key(h) for h in held), node.lineno))
            else:
                _, node, held = ev
                if isinstance(node, ast.Call):
                    for g in _resolve(node, fi, model):
                        csites.append((g, node.lineno))
                        evs.append(("call", g,
                                    tuple(key(h) for h in held), node.lineno))
        fns[id(fi)] = fi
        direct[id(fi)] = acq
        calls[id(fi)] = csites
        events[id(fi)] = evs

    # module-level functions acquire nothing themselves but may call
    # methods; for transitive-acquire purposes give every remaining
    # function an (empty-direct) entry with its resolvable calls
    for fi in model.functions:
        if id(fi) in fns:
            continue
        csites = []
        for sub in ast.walk(fi.node):
            if isinstance(sub, ast.Call):
                for g in _resolve(sub, fi, model):
                    csites.append((g, sub.lineno))
        fns[id(fi)] = fi
        direct.setdefault(id(fi), set())
        calls[id(fi)] = csites

    # transitive closure: acquires(f) = direct(f) U acquires(callees)
    trans = {k: set(v) for k, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for fid, csites in calls.items():
            for g, _line in csites:
                add = trans.get(id(g), ())
                if not set(add) <= trans[fid]:
                    trans[fid] |= add
                    changed = True

    # edges: (a, b) -> (path, line, qualname, note); first occurrence wins,
    # deterministically (functions iterate in file/definition order)
    edges = {}

    def add_edge(a, b, mod, line, qual, note):
        if (a, b) not in edges:
            edges[(a, b)] = (mod.relpath, line, qual, note)

    for fid, evs in events.items():
        fi = fns[fid]
        for ev in evs:
            if ev[0] == "acquire":
                _, k, held, line = ev
                for h in held:
                    add_edge(h, k, fi.module, line, fi.qualname,
                             f"acquires {k} while holding {h}")
            else:
                _, g, held, line = ev
                if not held:
                    continue
                for k in sorted(trans.get(id(g), ())):
                    for h in held:
                        add_edge(h, k, fi.module, line, fi.qualname,
                                 f"calls {g.qualname} (which may acquire "
                                 f"{k}) while holding {h}")

    # cycle detection: report each strongly connected component with a
    # cycle (>1 node, or a self-loop) exactly once, with a representative
    # chain reconstructed inside the SCC
    adj = defaultdict(set)
    for a, b in edges:
        adj[a].add(b)
    for comp in _sccs(adj):
        comp_set = set(comp)
        if len(comp) == 1:
            n = comp[0]
            if n not in adj[n]:
                continue
            chain = [n, n]
        else:
            chain = _cycle_in(sorted(comp_set)[0], comp_set, adj)
        hops = []
        for a, b in zip(chain, chain[1:]):
            path, line, qual, _note = edges[(a, b)]
            hops.append(f"{a} -> {b} ({path}:{line} in {qual})")
        anchor = edges[(chain[0], chain[1])]
        yield Finding(
            RULE, anchor[0], anchor[1], 0,
            "lock-order cycle (deadlock hazard): " + "; ".join(hops)
            + " — pick one global acquisition order",
        )


def _sccs(adj):
    """Tarjan over the lock graph; deterministic node order."""
    nodes = sorted(set(adj) | {b for bs in adj.values() for b in bs})
    index = {}
    low = {}
    onstack = set()
    stack = []
    out = []
    counter = [0]

    def strong(v):
        # iterative Tarjan (the graph is tiny, but recursion depth should
        # not depend on lock count)
        work = [(v, iter(sorted(adj.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                elif w in onstack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                out.append(sorted(comp))

    for v in nodes:
        if v not in index:
            strong(v)
    return sorted(out)


def _cycle_in(start, comp, adj):
    """A representative cycle through ``start`` within one SCC, as a node
    chain [start, ..., start]."""
    # BFS back to start restricted to the component
    from collections import deque

    parent = {start: None}
    q = deque([start])
    while q:
        v = q.popleft()
        for w in sorted(adj.get(v, ())):
            if w not in comp:
                continue
            if w == start:
                path = []
                node = v
                while node is not None:
                    path.append(node)
                    node = parent[node]
                return list(reversed(path)) + [start]
            if w not in parent:
                parent[w] = v
                q.append(w)
    return [start, start]  # pragma: no cover - SCC guarantees a cycle
