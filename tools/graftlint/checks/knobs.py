"""env-knob-drift: every DFT_* knob is schema'd, documented, and agrees
on its default.

The deployment surface is a growing family of ``DFT_*`` environment
knobs. Two conventions keep them governable: reads resolve through an
``_EnvCfg`` schema (utils/config.py) or the ``utils/envutil.py`` helpers
(one boolean-coercion convention, one place to grep), and every knob has
a row in the canonical reference table in ``docs/OPERATIONS.md``
(between ``<!-- graftlint:knob-table:start/end -->`` markers). This
cross-artifact checker proves both directions:

- **ad-hoc reads** — a raw ``os.environ``/``os.getenv`` read of a
  ``DFT_*`` name anywhere but utils/config.py or utils/envutil.py is a
  finding: register the knob in an ``_EnvCfg`` schema or read it through
  ``envutil.env_flag/env_int/env_float/env_str``;
- **undocumented code knobs** — a knob registered in a schema tuple
  ``(type, "DFT_X", default)`` or an envutil helper call must appear in
  the doc table;
- **stale doc knobs** — a table row whose knob no code reads anymore is
  operator-facing fiction and is flagged at its line in the doc;
- **default drift** — where both sides are parseable (a literal code
  default, a simple token in the table's Default column), they must
  agree; booleans normalize across 1/true/on, floats numerically,
  None across unset/none. Computed defaults (``min(8, cpus)``) and
  prose cells are skipped by design.

The doc-facing rules run only when the linted set contains a
``utils/config.py`` (the schema home), so single-file ``--changed``
lints stay fast and fixture lints stay self-contained: the doc is
resolved relative to the package root (``<pkg>/../docs/OPERATIONS.md``,
falling back to ``docs/OPERATIONS.md``).
"""

import ast
import os
import re

from tools.graftlint.core import Finding, dotted

RULE = "env-knob-drift"

_KNOB_RE = re.compile(r"^DFT_[A-Z0-9_]+$")
_ENVUTIL_HELPERS = frozenset({"env_flag", "env_int", "env_float", "env_str"})
_TABLE_START = "graftlint:knob-table:start"
_TABLE_END = "graftlint:knob-table:end"

_SANCTIONED_SUFFIXES = ("utils/config.py", "utils/envutil.py")


def _knob_literal(node):
    if (isinstance(node, ast.Constant) and isinstance(node.value, str)
            and _KNOB_RE.match(node.value)):
        return node.value
    return None


def _raw_env_reads(mod):
    """(knob, line, col) for raw os.environ / os.getenv reads of DFT_*."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if d in ("os.environ.get", "os.getenv", "environ.get") and node.args:
                knob = _knob_literal(node.args[0])
                if knob:
                    yield knob, node.lineno, node.col_offset
        elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Load):
            if dotted(node.value) in ("os.environ", "environ"):
                knob = _knob_literal(node.slice)
                if knob:
                    yield knob, node.lineno, node.col_offset


def _schema_knobs(mod):
    """(knob, default ast node, line) from ``(type, "DFT_X", default)``
    schema tuples anywhere in a config module."""
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Tuple) and len(node.elts) == 3):
            continue
        knob = _knob_literal(node.elts[1])
        if knob:
            yield knob, node.elts[2], node.lineno


_ABSENT = object()  # no default arg at the call site: the fallback is
# computed by the caller, so default-drift comparison is skipped


def _envutil_knobs(mod):
    """(knob, default ast node or _ABSENT, line) from envutil calls."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if name not in _ENVUTIL_HELPERS or not node.args:
            continue
        knob = _knob_literal(node.args[0])
        if not knob:
            continue
        default = node.args[1] if len(node.args) > 1 else _ABSENT
        for kw in node.keywords:
            if kw.arg == "default":
                default = kw.value
        yield knob, default, node.lineno


def _norm_default(text):
    """Normalize a default spelling to a comparable token, or None when
    it is prose/computed (skipped by design)."""
    t = text.strip().strip("`").strip("'\"").strip()
    if " " in t or "(" in t:
        return None
    low = t.lower()
    if low in ("1", "true", "on", "yes"):
        return "true"
    if low in ("0", "false", "off", "no"):
        return "false"
    if low in ("", "unset", "none", "-"):
        return "none"
    try:
        return repr(float(low))
    except ValueError:
        return low


def _norm_code_default(node):
    if node is _ABSENT:
        return None  # caller-computed fallback: unparseable by design
    if node is None:
        return "none"
    if not isinstance(node, ast.Constant):
        return None  # computed default: unparseable by design
    v = node.value
    if v is None:
        return "none"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return repr(float(v))
    if isinstance(v, str):
        return _norm_default(v)
    return None


def _defaults_agree(a, b) -> bool:
    """Token equality with the 0/1-vs-false/true ambiguity collapsed:
    a bool knob documented as `1` and an int knob documented as `1`
    normalize differently, but mean the same thing."""
    if a == b:
        return True
    for group in ({"true", "1.0"}, {"false", "0.0"}):
        if a in group and b in group:
            return True
    return False


def _parse_doc_table(doc_path):
    """{knob: (default cell text, line)} from the marked table."""
    with open(doc_path, "r", encoding="utf-8") as f:
        lines = f.read().splitlines()
    rows = {}
    inside = False
    for i, text in enumerate(lines, 1):
        if _TABLE_START in text:
            inside = True
            continue
        if _TABLE_END in text:
            break
        if not inside or not text.lstrip().startswith("|"):
            continue
        cells = [c.strip() for c in text.strip().strip("|").split("|")]
        if len(cells) < 2:
            continue
        knob = cells[0].strip("`").strip()
        if _KNOB_RE.match(knob):
            rows[knob] = (cells[1], i)
    return rows


def _find_doc(config_mod):
    """The OPERATIONS.md beside the linted package: try the package-local
    docs/ dir first (fixtures), then the repo-root one."""
    pkg_dir = os.path.dirname(os.path.dirname(config_mod.relpath))
    candidates = [
        os.path.join(pkg_dir, "docs", "OPERATIONS.md"),
        os.path.join(os.path.dirname(pkg_dir), "docs", "OPERATIONS.md"),
        os.path.join("docs", "OPERATIONS.md"),
    ]
    for c in candidates:
        if c and os.path.isfile(c):
            return c
    return None


def check(model):
    config_mod = None
    registered = {}   # knob -> (relpath, line, default node or "skip")
    read_anywhere = set()

    for mod in model.modules:
        sanctioned = mod.relpath.endswith(_SANCTIONED_SUFFIXES)
        if mod.relpath.endswith("utils/config.py"):
            config_mod = mod
            for knob, default, line in _schema_knobs(mod):
                registered.setdefault(knob, (mod.relpath, line, default))
                read_anywhere.add(knob)
        for knob, line, col in _raw_env_reads(mod):
            read_anywhere.add(knob)
            if not sanctioned:
                yield Finding(
                    RULE, mod.relpath, line, col,
                    f"ad-hoc environment read of {knob} — register it in "
                    "an _EnvCfg schema (utils/config.py) or read it "
                    "through utils/envutil.py so coercion and the knob "
                    "inventory cannot drift",
                )
        for knob, default, line in _envutil_knobs(mod):
            read_anywhere.add(knob)
            registered.setdefault(knob, (mod.relpath, line, default))

    if config_mod is None or model.subset:
        return  # per-module ad-hoc findings above are still exact; the
        # doc cross-check needs the full package — a subset lint cannot
        # tell a stale doc row from a knob whose reader just wasn't in
        # the changed set

    doc_path = _find_doc(config_mod)
    if doc_path is None:
        yield Finding(
            RULE, config_mod.relpath, 1, 0,
            "no docs/OPERATIONS.md knob table found for this package — "
            "the DFT_* knob inventory must be documented (markers "
            f"<!-- {_TABLE_START} --> / <!-- {_TABLE_END} -->)",
        )
        return
    doc_rows = _parse_doc_table(doc_path)
    doc_rel = doc_path.replace(os.sep, "/")

    for knob in sorted(registered):
        relpath, line, default = registered[knob]
        if knob not in doc_rows:
            yield Finding(
                RULE, relpath, line, 0,
                f"knob {knob} is read by the code but has no row in the "
                f"{doc_rel} knob table — undocumented deployment surface",
            )
            continue
        code_norm = _norm_code_default(default)
        doc_norm = _norm_default(doc_rows[knob][0])
        if code_norm is not None and doc_norm is not None \
                and not _defaults_agree(code_norm, doc_norm):
            yield Finding(
                RULE, doc_rel, doc_rows[knob][1], 0,
                f"knob {knob}: documented default "
                f"{doc_rows[knob][0]!r} disagrees with the code default "
                f"({relpath}:{line}) — operators will tune against "
                "fiction",
            )

    for knob in sorted(doc_rows):
        if knob not in read_anywhere:
            yield Finding(
                RULE, doc_rel, doc_rows[knob][1], 0,
                f"knob {knob} is documented but nothing reads it — stale "
                "doc row (or the knob lost its schema registration)",
            )
