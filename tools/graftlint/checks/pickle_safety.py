"""pickle-safety: network-facing unpickling goes through the allowlist.

Scope: ``parallel/`` — the modules that deserialize bytes received from a
socket. ``pickle.loads`` on wire bytes is remote code execution by design
(a crafted frame's GLOBAL/REDUCE opcodes call any importable callable);
the RPC skeleton must be decoded by ``rpc.restricted_loads``, whose
Unpickler resolves only numpy payload types, a safe builtins subset, and
this package's own RPC-surface classes (docs/LINTING.md#pickle-safety).

Engine-side ``pickle.load`` of local checkpoint files (meta.pkl,
buffer.pkl) is out of scope: those paths are operator-trusted storage,
not the network boundary.
"""

import ast

from tools.graftlint.core import Finding, attr_root, call_name

RULE = "pickle-safety"

_ALLOWED_QUALS = ("restricted_loads", "_RestrictedUnpickler")


def _in_scope(mod) -> bool:
    return "/parallel/" in mod.relpath or mod.relpath.startswith("parallel/")


def check(model):
    for mod in model.modules:
        if not _in_scope(mod):
            continue
        # spans of the restricted loader itself (the one place allowed to
        # touch pickle.Unpickler)
        allowed_spans = [
            (u.lineno, getattr(u.node, "end_lineno", u.lineno))
            for u in mod.units
            if any(part in _ALLOWED_QUALS for part in u.qualname.split("."))
        ]
        # the WHOLE module tree, not just function bodies: a module-level
        # `pickle.loads(...)` at the network boundary is just as hot
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name not in ("loads", "load", "Unpickler"):
                continue
            root = (attr_root(node.func)
                    if isinstance(node.func, ast.Attribute) else None)
            if root != "pickle":
                continue
            if any(a <= node.lineno <= b for a, b in allowed_spans):
                continue
            where = "<module>"
            for u in mod.units:
                end = getattr(u.node, "end_lineno", u.lineno)
                if u.lineno <= node.lineno <= end:
                    where = u.qualname
                    break
            yield Finding(
                RULE, mod.relpath, node.lineno, node.col_offset,
                f"bare pickle.{name} in network-facing {where}: "
                "use rpc.restricted_loads (allowlisted Unpickler) for "
                "wire payloads",
            )
