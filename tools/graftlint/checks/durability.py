"""generation-commit: storage-dir writes ride the atomic commit protocol.

A shard's storage dir is crash-safe only because every byte that lands
in it flows through ``serialization.atomic_write`` (tmp + fsync +
rename) and every generation becomes loadable only when its MANIFEST —
written LAST — commits it (utils/serialization.py, engine.py
``_commit_generation``). One direct ``open(..., 'w')`` into a storage
path reintroduces the reference system's torn-checkpoint bug the whole
layer exists to kill. This checker proves the discipline statically:

- **direct writes** — ``open(path, 'w'/'wb'/'a'/...)``, ``os.rename`` /
  ``os.replace``, and direct serializer dumps (``json.dump`` /
  ``pickle.dump`` / ``np.savez``) on a storage-tainted path are
  findings. Taint is name-based and precision-first: ``storage_dir`` /
  ``index_storage_dir`` parameters and attributes seed it, locals
  assigned from tainted expressions (``os.path.join(storage_dir, ...)``)
  propagate it.
- **one commit point** — ``serialization.write_manifest`` may be called
  only from ``_commit_generation`` (the shared protocol): a second
  manifest writer is a second, unreviewed definition of "committed".
- **MANIFEST last** — inside a committing function, no generation data
  file (an ``atomic_write`` whose path rides ``generation_filename``)
  may be written after the ``write_manifest`` call; the manifest IS the
  commit point, so anything after it is outside the crash contract.
- **fsync-before-rename** — a hand-rolled tmp-then-rename (``open(tmp,
  ...)`` then ``os.replace(tmp, dst)`` in one function) must ``fsync``
  between write and rename, or a power cut publishes a name whose bytes
  never hit the platter.

``utils/serialization.py`` itself is exempt from the sink rules (it IS
the sanctioned layer — quarantine renames, manifest writes) but not from
the fsync-ordering rule, which is how ``atomic_write`` stays honest.
"""

import ast
import os

from tools.graftlint.core import Finding, call_name, dotted

RULE = "generation-commit"

_TAINT_NAMES = frozenset({"storage_dir", "index_storage_dir"})
_SERIALIZERS = frozenset({"dump", "savez", "savez_compressed", "save"})
_SERIALIZER_ROOTS = frozenset({"json", "pickle", "np", "numpy"})


def _is_exempt(mod) -> bool:
    return mod.relpath.endswith("utils/serialization.py")


def _seed_tainted(node) -> bool:
    if isinstance(node, ast.Name) and node.id in _TAINT_NAMES:
        return True
    if isinstance(node, ast.Attribute) and node.attr in _TAINT_NAMES:
        return True
    return False


def _local_taint(fn_node) -> set:
    """Local names carrying a storage path, to a fixpoint: seeds are the
    taint-named parameters/attributes; ``v = <expr over tainted>``
    propagates."""
    tainted = set()
    args = getattr(fn_node, "args", None)
    if args is not None:
        for a in (args.args + args.kwonlyargs
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            if a is not None and a.arg in _TAINT_NAMES:
                tainted.add(a.arg)
    for _ in range(3):
        grew = False
        for sub in ast.walk(fn_node):
            if not isinstance(sub, ast.Assign):
                continue
            rhs_tainted = any(
                _seed_tainted(n) or (isinstance(n, ast.Name)
                                     and n.id in tainted)
                for n in ast.walk(sub.value))
            if not rhs_tainted:
                continue
            for t in sub.targets:
                if isinstance(t, ast.Name) and t.id not in tainted:
                    tainted.add(t.id)
                    grew = True
        if not grew:
            break
    return tainted


def _expr_tainted(expr, local_taint) -> bool:
    for n in ast.walk(expr):
        if _seed_tainted(n):
            return True
        if isinstance(n, ast.Name) and n.id in local_taint:
            return True
    return False


def _write_mode(call: ast.Call):
    """The literal mode string of an ``open`` call when it writes, else
    None (missing mode = 'r'; non-literal modes are invisible by
    design — precision over recall)."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if not (isinstance(mode, ast.Constant) and isinstance(mode.value, str)):
        return None
    return mode.value if any(c in mode.value for c in "wax+") else None


def _uses_generation_filename(call: ast.Call, genfile_locals) -> bool:
    for n in ast.walk(call):
        if isinstance(n, ast.Call) and call_name(n) == "generation_filename":
            return True
        if isinstance(n, ast.Name) and n.id in genfile_locals:
            return True
    return False


def check(model):
    for mod in model.modules:
        exempt = _is_exempt(mod)
        for fi in mod.functions:
            taint = _local_taint(fi.node)
            manifest_line = None
            genfile_locals = set()
            # locals assigned from generation_filename(...) — the names
            # of generation data files (MANIFEST-last ordering)
            for sub in ast.walk(fi.node):
                if isinstance(sub, ast.Assign) and isinstance(
                        sub.value, ast.Call):
                    if call_name(sub.value) == "generation_filename":
                        for t in sub.targets:
                            if isinstance(t, ast.Name):
                                genfile_locals.add(t.id)

            calls = [n for n in ast.walk(fi.node) if isinstance(n, ast.Call)]
            for call in calls:
                if call_name(call) == "write_manifest":
                    if manifest_line is None or call.lineno < manifest_line:
                        manifest_line = call.lineno
                    if not exempt and fi.name != "_commit_generation":
                        yield Finding(
                            RULE, mod.relpath, call.lineno, call.col_offset,
                            f"{fi.qualname} writes a MANIFEST directly — "
                            "generations commit only through the shared "
                            "_commit_generation protocol",
                        )

            for call in calls:
                name = call_name(call)
                d = dotted(call.func)

                # MANIFEST-last ordering (applies wherever manifests are
                # written, including _commit_generation itself)
                if (manifest_line is not None and name == "atomic_write"
                        and call.lineno > manifest_line
                        and _uses_generation_filename(call, genfile_locals)):
                    yield Finding(
                        RULE, mod.relpath, call.lineno, call.col_offset,
                        f"{fi.qualname} writes a generation data file "
                        "AFTER write_manifest — the manifest is the commit "
                        "point and must land last",
                    )

                if exempt:
                    continue

                if name == "open":
                    mode = _write_mode(call)
                    if mode and call.args and _expr_tainted(
                            call.args[0], taint):
                        yield Finding(
                            RULE, mod.relpath, call.lineno, call.col_offset,
                            f"{fi.qualname} opens a storage-dir path with "
                            f"mode {mode!r} directly — route the write "
                            "through serialization.atomic_write "
                            "(tmp+fsync+rename) and commit via "
                            "_commit_generation",
                        )
                elif d in ("os.rename", "os.replace"):
                    if any(_expr_tainted(a, taint) for a in call.args):
                        yield Finding(
                            RULE, mod.relpath, call.lineno, call.col_offset,
                            f"{fi.qualname} renames inside a storage dir "
                            "directly — only serialization.atomic_write's "
                            "fsync'd rename (or the quarantine helpers) "
                            "may move files there",
                        )
                elif (name in _SERIALIZERS and isinstance(
                        call.func, ast.Attribute)
                        and call.func.value is not None
                        and isinstance(call.func.value, ast.Name)
                        and call.func.value.id in _SERIALIZER_ROOTS):
                    if any(_expr_tainted(a, taint) for a in call.args):
                        yield Finding(
                            RULE, mod.relpath, call.lineno, call.col_offset,
                            f"{fi.qualname} serializes straight into a "
                            "storage-dir path — wrap the write in "
                            "serialization.atomic_write so a crash can "
                            "never publish a torn file",
                        )

            yield from _check_fsync_ordering(mod, fi)


def _check_fsync_ordering(mod, fi):
    """Hand-rolled tmp-then-rename: ``open(T, ...)`` followed by
    ``os.replace(T, ...)``/``os.rename(T, ...)`` on the same local name
    needs an ``os.fsync`` between write and rename."""
    opens = {}     # local name -> first open line
    fsync_lines = []
    renames = []   # (local name, line, col)
    for sub in ast.walk(fi.node):
        if not isinstance(sub, ast.Call):
            continue
        name = call_name(sub)
        d = dotted(sub.func)
        if name == "open" and sub.args and isinstance(sub.args[0], ast.Name):
            opens.setdefault(sub.args[0].id, sub.lineno)
        elif d == "os.fsync":
            fsync_lines.append(sub.lineno)
        elif d in ("os.replace", "os.rename") and sub.args and isinstance(
                sub.args[0], ast.Name):
            renames.append((sub.args[0].id, sub.lineno, sub.col_offset))
    for local, line, col in renames:
        open_line = opens.get(local)
        if open_line is None or open_line > line:
            continue
        if any(open_line <= fl <= line for fl in fsync_lines):
            continue
        yield Finding(
            RULE, mod.relpath, line, col,
            f"{fi.qualname} renames `{local}` into place without an "
            "os.fsync between write and rename — a power cut can publish "
            "a name whose bytes never reached disk; use "
            "serialization.atomic_write",
        )
