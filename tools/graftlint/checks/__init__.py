"""Checker registry. Each checker module exposes RULE and check(model)."""

from tools.graftlint.checks import (
    blocking,
    dtype,
    frame_protocol,
    host_sync,
    lock_order,
    locks,
    pallas_guard,
    pickle_safety,
    recompile,
)

ALL = (host_sync, recompile, dtype, locks, lock_order, blocking,
       frame_protocol, pallas_guard, pickle_safety)

RULES = {c.RULE: c for c in ALL}
