"""Checker registry. Each checker module exposes RULE and check(model)."""

from tools.graftlint.checks import (
    blocking,
    dtype,
    durability,
    exceptions,
    frame_protocol,
    host_sync,
    knobs,
    lock_order,
    locks,
    pallas_guard,
    pickle_safety,
    races,
    recompile,
    threads,
)

ALL = (host_sync, recompile, dtype, locks, lock_order, blocking,
       frame_protocol, pallas_guard, pickle_safety, threads, durability,
       knobs, exceptions, races)

RULES = {c.RULE: c for c in ALL}
