"""Checker registry. Each checker module exposes RULE and check(model)."""

from tools.graftlint.checks import (
    dtype,
    host_sync,
    locks,
    pallas_guard,
    pickle_safety,
    recompile,
)

ALL = (host_sync, recompile, dtype, locks, pallas_guard, pickle_safety)

RULES = {c.RULE: c for c in ALL}
