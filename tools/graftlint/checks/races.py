"""shared-state-race: Eraser-style whole-program lockset analysis.

The lock-discipline checker (checks/locks.py) polices the attributes a
human remembered to PIN; this checker closes the gap from the other end.
Over the shared thread-root model (core.ThreadRootModel) — every thread
entry point the package creates (named ``threading.Thread`` targets,
``ThreadPoolExecutor`` submissions, the public-API caller root) with an
interprocedural lockset walk — any attribute that is WRITTEN on one root
and touched on another with an EMPTY lockset intersection is a race
candidate: no common lock orders the two accesses, so the interleaving
that corrupts (or reads a torn view of) the attribute is one scheduler
decision away. Each finding carries per-root file:line provenance for
both sides of the offending pair.

Three reviewed escape hatches, in preference order:

- a PINS entry (checks/locks.py): the attribute is lock-guarded and the
  lock-discipline checker enforces every access — pinning is the fix for
  a real race;
- ``# graftlint: atomic(<attr>)`` inside the class body: a benign
  monotonic counter / publish-once flag / single-machine-word read whose
  staleness is acceptable (CPython's GIL makes the word-tear impossible;
  the annotation records that the STALENESS was reviewed). Prefer routing
  counters through ``utils/atomics.AtomicCounters`` over scattering
  these;
- ``ok(shared-state-race)`` at the finding line: a reviewed exception
  that is neither (rare; say why).

A stale ``atomic()`` marker — one that waives no live cross-root access
this run — is itself a finding (the atomic-rot half of the suppression
audit), so the reviewed-benign inventory cannot rot.

Precision notes: roots are a static proxy for thread identity, so two
threads spawned from the SAME root racing each other are invisible, as
is anything reached only through dynamic dispatch (``getattr`` RPC
dispatch, callbacks, function values) — zero findings is necessary, not
sufficient. ``utils/racecheck.py`` (the DFT_RACECHECK=1 runtime witness)
covers the dynamic half of the same contract, exactly as lockdep does
for lock-order. Cross-artifact by construction (thread roots live in
other modules), so the whole rule gates off on subset (``--changed``)
lints.
"""

from collections import defaultdict

from tools.graftlint.core import Finding, thread_root_model

RULE = "shared-state-race"


def _atomic_map(model):
    """((cls, attr) -> [(module, line)]) for every ``atomic()`` marker,
    resolved to the class whose lexical span contains the comment, plus
    the flat list of all markers for the rot audit."""
    by_key = defaultdict(list)
    markers = []  # (module, line, attrs, cls-or-None)
    for mod in model.modules:
        for line, attrs in sorted(mod.atomic_marks.items()):
            owner = None
            for cnode in mod.classes:
                end = getattr(cnode, "end_lineno", cnode.lineno)
                if cnode.lineno <= line <= end:
                    owner = cnode.name
                    break
            markers.append((mod, line, attrs, owner))
            if owner is not None:
                for attr in attrs:
                    by_key[(owner, attr)].append((mod, line))
    return by_key, markers


def _fmt_locks(locks) -> str:
    return "{" + ", ".join(sorted(locks)) + "}" if locks else "no locks"


def check(model):
    if model.subset:
        # thread roots (and the atomic-rot audit) are only decidable
        # against the full package: a subset lint would see an attribute's
        # accesses without the thread that races them — or a live atomic()
        # marker as rot
        return
    from tools.graftlint.checks.locks import PINS

    trm = thread_root_model(model)
    by_key = defaultdict(list)
    for acc in trm.accesses:
        by_key[(acc.cls, acc.attr)].append(acc)

    atomic_by_key, markers = _atomic_map(model)
    used_marker_lines = set()  # (id(module), line)

    for (cls, attr), accs in sorted(by_key.items()):
        if (cls, attr) in PINS:
            continue  # lock-guarded: lock-discipline enforces every access
        if len({a.root for a in accs}) < 2:
            continue
        pair = None
        for w in accs:
            if not w.write:
                continue
            for b in accs:
                if b.root == w.root or (w.locks & b.locks):
                    continue
                cand = (w, b)
                if pair is None or (cand[0].line, cand[1].line) < (
                        pair[0].line, pair[1].line):
                    pair = cand
            if pair is not None:
                break  # accesses are sorted: the first racy write anchors
        if pair is None:
            continue
        marks = atomic_by_key.get((cls, attr))
        if marks:
            used_marker_lines.update((id(m), ln) for m, ln in marks)
            continue
        w, b = pair
        verb = "written" if b.write else "read"
        yield Finding(
            RULE, w.path, w.line, w.col,
            f"{cls}.{attr} is written on root `{w.root}` "
            f"({w.path}:{w.line} in {w.func}, {_fmt_locks(w.locks)}) and "
            f"{verb} on root `{b.root}` ({b.path}:{b.line} in {b.func}, "
            f"{_fmt_locks(b.locks)}) with an empty lockset intersection — "
            "no lock orders the two threads. Pin it in the lock map "
            "(checks/locks.py PINS), guard both sides, or annotate "
            f"`# graftlint: atomic({attr})` for a benign monotonic "
            "counter/flag",
        )

    # atomic-rot audit: a marker that waived nothing this run is itself a
    # finding — exactly the ok() rot contract, for the atomic() syntax
    for mod, line, attrs, owner in markers:
        if (id(mod), line) in used_marker_lines:
            continue
        if owner is None:
            why = ("it is outside any class body, so it can never cover "
                   "an attribute")
        else:
            why = (f"no cross-root unsynchronized access to "
                   f"{owner}.{{{', '.join(sorted(attrs))}}} exists this "
                   "run (the race it waived was fixed, or the attr is "
                   "gone)")
        yield Finding(
            RULE, mod.relpath, line, 0,
            f"stale atomic({', '.join(sorted(attrs))}) marker: {why} — "
            "delete it, or fix the spelling",
        )
