"""lock-discipline: mutable shared state is only touched under its lock.

Scope: every linted class that owns a ``threading.Lock``/``RLock``
attribute. Two sources decide which attributes a lock guards:

- PINS: the reviewed engine/server map (the invariants PR 1's hot path
  depends on — ``Index.tpu_index``/``state`` under ``index_lock``,
  ``Index.embeddings_buffer``/``total_data``/``id_to_metadata`` under
  ``buffer_lock``, ``IndexServer.indexes`` under ``indexes_lock``).
- Inference for every other lock-owning class: an attribute accessed under
  lock L in a STRICT MAJORITY of its uses is considered L-guarded, and the
  minority accesses are findings. (Majority, not unanimity — otherwise the
  violation being hunted would vote its own attribute out of the guarded
  set.)

Lexical model: a ``with self.<lock>:`` block activates the lock for its
body. Lambdas inherit the surrounding lock context (they run inline —
e.g. the atomic-save write lambdas); nested ``def``s reset it (they
usually run later on another thread, e.g. watcher/worker targets).
``__init__``/``__new__``/``__del__`` are construction/teardown
(single-threaded by contract) and are skipped.
"""

import ast

from tools.graftlint.core import Finding, lock_attrs

RULE = "lock-discipline"

PINS = {
    ("Index", "tpu_index"): "index_lock",
    ("Index", "state"): "index_lock",
    ("Index", "embeddings_buffer"): "buffer_lock",
    ("Index", "total_data"): "buffer_lock",
    ("Index", "id_to_metadata"): "buffer_lock",
    ("IndexServer", "indexes"): "indexes_lock",
    # chaos harness thread state (testing/chaos.py): the live-socket list
    # is appended by per-connection handler threads and drained by stop();
    # the fault plan cursor and default fault are read/advanced per accept
    ("ChaosProxy", "_conns"): "_lock",
    ("ChaosProxy", "_threads"): "_lock",
    ("ChaosProxy", "_accepted"): "_lock",
    ("ChaosProxy", "_default_fault"): "_lock",
    ("ServerHarness", "procs"): "_lock",
    # serving scheduler thread state (serving/scheduler.py): the request
    # queue and admission counters are shared between every connection
    # thread (submit) and the batcher thread, all under the flush condition;
    # the server's tracked async-training threads live under their own lock
    ("SearchScheduler", "_queue"): "_cond",
    ("SearchScheduler", "_stopping"): "_cond",
    # the shared atomic-counter helper (utils/atomics.py): every counter
    # mutation and snapshot rides the bundle's own leaf lock — scheduler
    # admission counters and client fan-out totals route through it
    # instead of borrowing a broader lock (or an atomic() annotation)
    ("AtomicCounters", "_counts"): "_lock",
    ("IndexServer", "_train_threads"): "_threads_lock",
    # RPC multiplexing thread state (parallel/rpc.py, parallel/server.py):
    # the client's in-flight slot table and connection generation are
    # shared between callers, the demux reader, and teardown; the server's
    # in-flight gauge/counters between connection readers and the worker
    # pool's response writers
    ("Client", "_pending"): "_lock",
    ("Client", "_closed"): "_lock",
    ("Client", "_epoch"): "_lock",
    ("Client", "_inflight_peak"): "_lock",
    ("Client", "_last_rx"): "_lock",
    ("Client", "_peer_tagged"): "_lock",
    ("IndexServer", "_mux_inflight"): "_mux_lock",
    ("IndexServer", "_mux_counters"): "_mux_lock",
    # replication membership/repair state (parallel/replication.py,
    # parallel/client.py): the group table is read by every fan-out and
    # rewritten by online join/leave; the repair queue is appended by the
    # write path and drained by the background repair pass; the client's
    # reroute ring, fan-out counters, and per-group read pins are shared
    # between user threads and the fan-out pool's workers
    ("MembershipTable", "_groups"): "_lock",
    ("MembershipTable", "_group_of"): "_lock",
    ("RepairQueue", "_items"): "_lock",
    ("RepairQueue", "_counters"): "_lock",
    ("IndexClient", "reroutes"): "_stats_lock",
    ("IndexClient", "_preferred"): "_stats_lock",
    # chaos query-storm collector (testing/chaos.py): results/errors are
    # appended by N storm threads and drained by stop()
    ("QueryStorm", "results"): "_lock",
    ("QueryStorm", "errors"): "_lock",
    # mutation subsystem (engine.py + mutation/): the tombstone set rides
    # index_lock — the SAME lock every device search and the mask scatter
    # hold, which is the no-torn-mask-mid-window guarantee; the metadata
    # layout epoch (compaction-swap seqlock) rides buffer_lock, the join
    # side. The compaction watcher thread (mutation/compaction.py
    # run_watcher) takes only these pinned engine locks.
    ("Index", "tombstones"): "index_lock",
    ("Index", "_mutation_counters"): "index_lock",
    ("Index", "_meta_epoch"): "buffer_lock",
    # standalone-sidecar writer: payload versions are assigned under
    # index_lock (with the set mutation); the disk write + written-version
    # watermark ride a dedicated leaf lock so a delete storm's fsyncs
    # never stall the serving locks
    ("Index", "_tombstone_version"): "index_lock",
    ("Index", "_tombstone_written"): "_tombstone_io_lock",
    # anti-entropy subsystem (parallel/antientropy.py + engine/client
    # wiring): the cached replica digest rides index_lock (read/written
    # under both engine locks; add_batch's ledger-prune invalidation
    # holds index_lock alone); the health table's peer/inbound maps are
    # shared between the sweeper thread and the worker pool's
    # _serve_digest handlers; the sweeper's counters between the sweep
    # loop and perf-stats readers; the client's suspect set between
    # refresh_health (repair driver thread) and every read fan-out; the
    # repair queue's drop-warning clock rides its own lock like the
    # counters beside it
    ("Index", "_digest_cache"): "index_lock",
    ("IndexServer", "_dropped"): "indexes_lock",
    ("HealthTable", "_peers"): "_lock",
    ("HealthTable", "_inbound"): "_lock",
    ("AntiEntropySweeper", "_counters"): "_lock",
    ("AntiEntropySweeper", "_last_empty_warn"): "_lock",
    ("IndexClient", "_suspects"): "_stats_lock",
    ("RepairQueue", "_last_drop_warn"): "_lock",
    # per-id mutation versioning (ISSUE 12, mutation/versions.py +
    # engine/client wiring): the engine's per-writer watermark dict rides
    # index_lock with the rest of the version state (per-id versions live
    # inside the TombstoneSet under the same lock); the pinned-generation
    # snapshot cache has its own leaf lock so point-in-time reads never
    # contend with the serving locks; the client's HLC bookkeeping
    # (clock-seeded index set, last-stamp map for read-your-writes, the
    # legacy-rank degrade set) is shared between user threads and the
    # fan-out pool under the stats lock; the HLC's own physical/logical
    # counters between every stamping thread
    ("Index", "_version_watermark"): "index_lock",
    ("Index", "_saved_tombstone_version"): "index_lock",
    # the snapshot-generation counter is written under BOTH engine locks
    # (save/compact hold them together), so majority inference flaps
    # between the two on set order — pin the read side's lock: the
    # pinned-read path (current_generation) snapshots it under
    # index_lock alone
    ("Index", "_generation"): "index_lock",
    ("Index", "_pinned_cache"): "_pinned_lock",
    ("IndexClient", "_seeded"): "_stats_lock",
    ("IndexClient", "_last_write_version"): "_stats_lock",
    ("IndexClient", "_unversioned_ranks"): "_stats_lock",
    ("HLC", "_last_ms"): "_lock",
    ("HLC", "_counter"): "_lock",
    # observability subsystem (observability/spans.py): the span ring is
    # appended by every serving stage of a sampled request — connection
    # readers, the scheduler's batcher, worker-pool response writers,
    # client fan-out threads — and snapshotted by the get_trace_spans op
    # and the perf-stats tracing block
    ("SpanBuffer", "_spans"): "_lock",
    ("SpanBuffer", "_counters"): "_lock",
}

# the modules the pinned classes live in: the frame-protocol stale-pin
# audit runs only when ALL of these are in the linted set (a full lint),
# so fixture lints and `--changed` subsets — which legitimately lack
# some pinned classes — don't report every absent class as a stale pin
PIN_HOMES = (
    "engine.py",
    "utils/atomics.py",
    "serving/scheduler.py",
    "parallel/rpc.py",
    "parallel/server.py",
    "parallel/client.py",
    "parallel/replication.py",
    "parallel/antientropy.py",
    "mutation/versions.py",
    "observability/spans.py",
    "testing/chaos.py",
)

_SKIP_METHODS = frozenset({"__init__", "__new__", "__del__"})


# lock-attr detection lives in core (shared with lock-order,
# blocking-under-lock, and the frame-protocol stale-pin audit): it
# recognizes both ``threading.Lock()``-style constructors and the
# ``lockdep.lock/rlock/condition(...)`` runtime-witness factories.
_lock_attrs = lock_attrs


_MUTATOR_METHODS = frozenset({
    "append", "extend", "insert", "pop", "popitem", "remove", "clear",
    "update", "setdefault", "add", "discard", "sort", "reverse",
})


def _self_attr(node) -> str:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return ""


def _mutated_attrs(class_node) -> set:
    """Attributes mutated in any method OTHER than construction/teardown —
    only mutable state needs a lock. Mutation = rebinding (``self.x = ...``),
    item assignment (``self.x[k] = ...``), or an in-place container method
    (``self.x.append(...)``)."""
    out = set()
    for sub in class_node.body:
        if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if sub.name in _SKIP_METHODS:
            continue
        for node in ast.walk(sub):
            if isinstance(node, ast.Attribute) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                attr = _self_attr(node)
                if attr:
                    out.add(attr)
            elif isinstance(node, ast.Subscript) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                attr = _self_attr(node.value)
                if attr:
                    out.add(attr)
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATOR_METHODS):
                attr = _self_attr(node.func.value)
                if attr:
                    out.add(attr)
    return out


class _Access:
    __slots__ = ("attr", "line", "col", "locks_held", "method")

    def __init__(self, attr, line, col, locks_held, method):
        self.attr = attr
        self.line = line
        self.col = col
        self.locks_held = locks_held
        self.method = method


def _collect_accesses(method_node, lock_names, method_name):
    accesses = []

    def visit(node, held):
        if isinstance(node, ast.With):
            new_held = set(held)
            for item in node.items:
                ce = item.context_expr
                if (isinstance(ce, ast.Attribute)
                        and isinstance(ce.value, ast.Name)
                        and ce.value.id == "self" and ce.attr in lock_names):
                    new_held.add(ce.attr)
            for sub in node.body:
                visit(sub, frozenset(new_held))
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in node.body:
                visit(sub, frozenset())  # runs later: no inherited locks
            return
        if isinstance(node, ast.Lambda):
            visit(node.body, held)  # runs inline: inherits lock context
            return
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr not in lock_names):
            accesses.append(_Access(node.attr, node.lineno, node.col_offset,
                                    held, method_name))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in method_node.body:
        visit(stmt, frozenset())
    return accesses


def check(model):
    for mod in model.modules:
        for node in mod.classes:
            lock_names = _lock_attrs(node)
            if not lock_names:
                continue
            accesses = []
            for sub in node.body:
                if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if sub.name in _SKIP_METHODS:
                    continue
                accesses += _collect_accesses(sub, lock_names, sub.name)

            # attribute -> guarding lock: pins first, then majority vote
            guarded = {}
            for (cls, attr), lock in PINS.items():
                if cls == node.name and lock in lock_names:
                    guarded[attr] = lock
            mutated = _mutated_attrs(node)
            by_attr = {}
            for a in accesses:
                by_attr.setdefault(a.attr, []).append(a)
            for attr, uses in by_attr.items():
                if attr in guarded:
                    continue
                if attr not in mutated:
                    continue  # immutable after construction: lock-free reads are fine
                for lock in lock_names:
                    under = sum(1 for a in uses if lock in a.locks_held)
                    if under * 2 > len(uses):
                        guarded[attr] = lock
                        break

            for a in accesses:
                lock = guarded.get(a.attr)
                if lock is None or lock in a.locks_held:
                    continue
                yield Finding(
                    RULE, mod.relpath, a.line, a.col,
                    f"{node.name}.{a.method} touches `self.{a.attr}` outside "
                    f"`with self.{lock}` (guarded attribute)",
                )
