"""exception-classification: broad excepts on RPC paths must classify.

The retry/reroute/failover machinery (parallel/) is driven entirely by
exception CLASS: ``TRANSPORT_ERRORS`` retry and fail over,
``RETRYABLE_ERRORS`` adds BUSY backpressure, ``ServerException`` is an
application error that must never trigger failover. A broad ``except
Exception`` that silently swallows on one of these paths erases the
signal the whole layer dispatches on — a dead peer looks like a healthy
no-op. Scoped to ``parallel/`` modules, this checker flags:

- **bare excepts** — ``except:`` catches ``SystemExit`` /
  ``KeyboardInterrupt``; a serving loop that eats those cannot be shut
  down. Only acceptable when the handler re-raises.
- **silent broad swallows** — an ``except Exception`` /
  ``except BaseException`` handler that neither raises, nor references
  the caught exception (recording it into an outcome/error structure is
  classification), nor names a classification surface
  (``TRANSPORT_ERRORS`` / ``RETRYABLE_ERRORS`` / ``ServerException`` /
  ``MultiRankError`` / a ``classify`` helper), nor at minimum logs it
  (``logger.exception/error/warning``). Deliberate duck-typing probes
  carry ``# graftlint: ok(exception-classification): <reason>``.
- **ungated retries** — a broad handler whose body ``continue``s a
  retry loop: retrying on *everything* turns a deterministic application
  error into an infinite loop; gate the except on ``RETRYABLE_ERRORS``
  (or ``TRANSPORT_ERRORS`` + the specific classes the loop can heal).
- **hot-path swallow-and-pass** — a broad ``except: pass`` inside a
  function on the serving hot path (the core hot-walk) is a silent
  wrong-answer generator under load.
"""

import ast

from tools.graftlint.core import Finding

RULE = "exception-classification"

_BROAD = frozenset({"Exception", "BaseException"})

# referencing any of these in a handler body counts as classification:
# the exception is being sorted into the wire taxonomy, not swallowed
_CLASSIFIERS = frozenset({
    "TRANSPORT_ERRORS", "RETRYABLE_ERRORS", "ServerException",
    "MultiRankError", "QuorumError", "BusyError", "FrameError",
    "ClientExit", "DeadlineExceeded",
})

_LOG_METHODS = frozenset({"exception", "error", "warning"})


def _in_scope(mod) -> bool:
    rel = mod.relpath
    return "/parallel/" in rel or rel.startswith("parallel/")


def _terminal_name(node):
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _is_broad(handler) -> bool:
    if handler.type is None:
        return True
    return _terminal_name(handler.type) in _BROAD


def _body_traits(handler):
    traits = {
        "raise": False, "log": False, "refs_exc": False,
        "classifier": False, "continue": False,
    }
    for sub in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(sub, ast.Raise):
            traits["raise"] = True
        elif isinstance(sub, ast.Continue):
            traits["continue"] = True
        elif (isinstance(sub, ast.Name) and handler.name
                and sub.id == handler.name):
            traits["refs_exc"] = True
        elif _terminal_name(sub) in _CLASSIFIERS:
            traits["classifier"] = True
        elif isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Attribute) and f.attr in _LOG_METHODS:
                traits["log"] = True
            name = _terminal_name(f)
            if name and "classify" in name.lower():
                traits["classifier"] = True
    return traits


def _only_pass(handler) -> bool:
    return all(isinstance(s, ast.Pass) for s in handler.body)


def check(model):
    for mod in model.modules:
        if not _in_scope(mod):
            continue
        for fi in mod.functions:
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Try):
                    continue
                for handler in node.handlers:
                    if not _is_broad(handler):
                        continue
                    t = _body_traits(handler)
                    line, col = handler.lineno, handler.col_offset
                    if handler.type is None and not t["raise"]:
                        yield Finding(
                            RULE, mod.relpath, line, col,
                            f"{fi.qualname}: bare `except:` swallows "
                            "SystemExit/KeyboardInterrupt — catch "
                            "Exception (or a classified tuple) or "
                            "re-raise",
                        )
                        continue
                    if _only_pass(handler) and fi.hot:
                        yield Finding(
                            RULE, mod.relpath, line, col,
                            f"{fi.qualname}: broad swallow-and-pass on a "
                            "hot-path function — under load this "
                            "silently converts failures into wrong "
                            "answers; classify into TRANSPORT_ERRORS/"
                            "ServerException or let it propagate",
                        )
                        continue
                    if t["continue"] and not t["classifier"]:
                        yield Finding(
                            RULE, mod.relpath, line, col,
                            f"{fi.qualname}: broad except retries "
                            "(`continue`) on ANY failure — a "
                            "deterministic application error becomes an "
                            "infinite loop; gate the handler on "
                            "RETRYABLE_ERRORS/TRANSPORT_ERRORS",
                        )
                        continue
                    if not (t["raise"] or t["log"] or t["refs_exc"]
                            or t["classifier"]):
                        yield Finding(
                            RULE, mod.relpath, line, col,
                            f"{fi.qualname}: broad except swallows the "
                            "exception without re-raising, classifying "
                            "(TRANSPORT_ERRORS/ServerException), "
                            "recording, or logging it — the retry/"
                            "failover machinery dispatches on exception "
                            "class and this erases the signal",
                        )
