"""blocking-under-lock: no unbounded blocking while a lock is held.

A thread that blocks indefinitely while holding a lock turns every other
user of that lock into a hostage: a peer that stops draining TCP, a
child that never exits, or a device launch that wedges the runtime
freezes the whole serving surface behind one stuck thread. This checker
flags, inside any ``with self.<lock>:`` body (lexical model shared with
lock-discipline — lambdas inherit, nested defs reset):

- socket operations that can block without bound (``sendall``, ``recv``,
  ``recv_into``, ``recvfrom``, ``sendto``, ``accept``);
- unbounded joins/waits: zero-argument ``.join()`` (``str.join`` always
  takes an argument, so bare ``join()`` is Thread/Process/greenlet
  style), zero-argument ``.wait()`` (Event/Condition/Popen without a
  timeout), zero-argument ``.get()`` (blocking queue pop — ``dict.get``
  always takes a key);
- ``time.sleep`` (bounded, but a lock is exactly the wrong place to
  spend the bound);
- jitted device launches: a call that (transitively, over the name-based
  call graph) reaches a ``jax.jit``-decorated function or a Pallas
  kernel. A launch can recompile or wedge the runtime for unbounded
  time; the engine's designed locked launch (one in-flight device search
  per index) carries a reasoned suppression instead.

Indirect blocking propagates through PRECISELY resolvable calls only
(bare names preferring same-module definitions, exact ``self.method()``
dispatch — the lock-order checker's resolution), so hiding ``sendall``
one helper down (``rpc._send_parts``) still flags the locked caller,
while a ``search`` on some other object never inherits an unrelated
class's ``search``. Launch detection is deliberately looser (attribute
names minus the stoplist): model entry points are reached through
``self.tpu_index.<method>`` dynamic dispatch, which exact resolution
cannot see. Audited, deliberate sites — the serial RPC client that
holds its stub lock across a round trip by definition, the
SO_SNDTIMEO-bounded mux frame write — carry
``# graftlint: ok(blocking-under-lock): <reason>``.
"""

import ast
from collections import defaultdict

from tools.graftlint.core import (
    EXTERNAL_ROOTS,
    Finding,
    HOT_EDGE_STOPLIST,
    attr_root,
    call_name,
    dotted,
    lock_attrs,
    lock_context_events,
    registry_launch_names,
)

RULE = "blocking-under-lock"

_SKIP_METHODS = frozenset({"__init__", "__new__", "__del__"})

_SOCKET_BLOCKING = frozenset({
    "sendall", "recv", "recv_into", "recvfrom", "sendto", "accept",
})

# zero-argument spellings of these attribute calls block without bound;
# any argument (timeout positional/keyword, str.join's iterable, a dict
# key) makes them bounded or a different method entirely
_ZERO_ARG_BLOCKING = {
    "join": "unbounded .join()",
    "wait": "untimed .wait()",
    "get": "blocking .get()",
}


def _direct_reason(call: ast.Call):
    """Reason string when this call blocks by itself, else None."""
    f = call.func
    if isinstance(f, ast.Attribute):
        if f.attr in _SOCKET_BLOCKING:
            return f"socket .{f.attr}()"
        if (f.attr in _ZERO_ARG_BLOCKING and not call.args
                and not call.keywords):
            return _ZERO_ARG_BLOCKING[f.attr]
    dn = dotted(f)
    if dn == "time.sleep":
        return "time.sleep()"
    return None


def _callee_names(call: ast.Call):
    """Names a call site may resolve through, for blocking/launch
    propagation: bare names, and attribute calls NOT rooted in an
    external module alias. Stoplisted ubiquitous names never carry."""
    f = call.func
    if isinstance(f, ast.Name):
        if f.id not in HOT_EDGE_STOPLIST:
            yield f.id
    elif isinstance(f, ast.Attribute):
        root = attr_root(f)
        if root in EXTERNAL_ROOTS:
            return
        if f.attr not in HOT_EDGE_STOPLIST:
            yield f.attr


def _may_block(model):
    """function id -> reason for every repo function that may block,
    directly or through PRECISELY resolved calls (lock_order._resolve:
    bare names preferring same-module definitions, else a globally unique
    one; exact ``self.method()`` dispatch)."""
    from tools.graftlint.checks.lock_order import _resolve

    reasons = {}   # function id -> reason
    callers = defaultdict(set)  # callee id -> set of caller fids
    for fi in model.functions:
        for sub in ast.walk(fi.node):
            if not isinstance(sub, ast.Call):
                continue
            r = _direct_reason(sub)
            if r is not None and id(fi) not in reasons:
                reasons[id(fi)] = r
            for g in _resolve(sub, fi, model):
                callers[id(g)].add(id(fi))
    # propagate callee->caller to a fixpoint
    fns = {id(fi): fi for fi in model.functions}
    work = list(reasons)
    while work:
        fid = work.pop()
        for cid in callers.get(fid, ()):
            if cid not in reasons:
                reasons[cid] = (f"calls {fns[fid].qualname}: "
                                f"{reasons[fid]}")
                work.append(cid)
    return reasons


def _may_launch(model):
    """Names of repo functions that may launch a jitted device program
    (directly jitted, calling a jitted name or a Pallas entry, or
    reaching one transitively)."""
    launching = set()  # function ids
    callers = defaultdict(set)
    fns = {}
    # the jit-entry registry's launch targets (utils/jitreg.py, parsed by
    # core.registry_launch_names) are launch-semantic by declaration —
    # unioned with the per-module jit scan so the registry, HOT_ROOTS and
    # this checker can't drift apart on what "a launch" is
    registry_names = registry_launch_names()
    for fi in model.functions:
        fns[id(fi)] = fi
        if fi.jit is not None or fi.name in registry_names:
            launching.add(id(fi))
        for sub in ast.walk(fi.node):
            if not isinstance(sub, ast.Call):
                continue
            cn = call_name(sub)
            if cn in ("pallas_call", "pallas_guarded") or (
                    cn in registry_names) or (
                    cn in model.jitted_names and cn not in HOT_EDGE_STOPLIST):
                launching.add(id(fi))
            for name in _callee_names(sub):
                callers[name].add(id(fi))
    work = list(launching)
    while work:
        fid = work.pop()
        for cid in callers.get(fns[fid].name, ()):
            if cid not in launching:
                launching.add(cid)
                work.append(cid)
    return {fns[fid].name for fid in launching} - HOT_EDGE_STOPLIST


def check(model):
    from tools.graftlint.checks.lock_order import _resolve

    blocking = _may_block(model)
    launch_names = _may_launch(model)

    lock_names_by_cls = {}
    for mod in model.modules:
        for cnode in mod.classes:
            names = lock_attrs(cnode)
            if names:
                lock_names_by_cls[(id(mod), cnode.name)] = names

    for fi in model.functions:
        if fi.cls is None or fi.name in _SKIP_METHODS:
            continue
        lock_names = lock_names_by_cls.get((id(fi.module), fi.cls))
        if not lock_names:
            continue
        for ev in lock_context_events(fi.node, lock_names):
            if ev[0] != "node":
                continue
            _, node, held = ev
            if not held or not isinstance(node, ast.Call):
                continue
            reason = _direct_reason(node)
            if reason is None:
                for g in _resolve(node, fi, model):
                    if id(g) in blocking:
                        reason = (f"`{g.qualname}` may block "
                                  f"({blocking[id(g)]})")
                        break
            if reason is None:
                for name in _callee_names(node):
                    if name in launch_names:
                        reason = (f"`{name}` may launch a jitted "
                                  "device program")
                        break
            if reason is None:
                continue
            locks = ", ".join(f"self.{h}" for h in held)
            yield Finding(
                RULE, fi.module.relpath, node.lineno, node.col_offset,
                f"{fi.cls}.{fi.name} holds {locks} across a "
                f"potentially unbounded blocking call: {reason}",
            )
