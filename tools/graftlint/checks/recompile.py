"""recompile-hazard: jit signatures that retrace or recompile per call.

- R1: a jitted function whose parameter is annotated ``int``/``bool``/
  ``str`` (or defaulted to such a constant) but is not listed in
  ``static_argnames``/``static_argnums`` gets a fresh trace per distinct
  value — on the serving path that is a recompile storm (every (k, nprobe)
  combination compiles a multi-second program).
- R2: Python ``if``/``while`` branching on a non-static parameter inside a
  jitted function is a trace-time branch on a traced value and raises a
  ConcretizationTypeError at best, silently bakes one branch in at worst.
  ``is None``/``is not None`` structural checks are exempt.
- R3: calling ``jax.jit(...)`` inline inside a function body creates a
  fresh cache entry per call (the inner callable is a new object each
  time); hoist to module level or bind once in ``__init__``.
"""

import ast

from tools.graftlint.core import (
    Finding, decorator_jit_info, jit_info_from_call,
)

RULE = "recompile-hazard"

_SCALAR_ANN = frozenset({"int", "bool", "str"})


def _params(node):
    a = node.args
    return list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)


def _scalar_param_names(node):
    """Parameter names whose annotation or default marks them as Python
    scalars (with positional indexes for static_argnums matching)."""
    a = node.args
    pos = list(a.posonlyargs) + list(a.args)
    out = {}
    defaults = [None] * (len(pos) - len(a.defaults)) + list(a.defaults)
    for i, (p, d) in enumerate(zip(pos, defaults)):
        if _is_scalar(p.annotation, d):
            out[p.arg] = i
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if _is_scalar(p.annotation, d):
            out[p.arg] = None
    return out


def _is_scalar(annotation, default) -> bool:
    if isinstance(annotation, ast.Name) and annotation.id in _SCALAR_ANN:
        return True
    if (isinstance(default, ast.Constant)
            and isinstance(default.value, (bool, int, str))
            and default.value is not None):
        return True
    return False


def _structural(test: ast.AST) -> bool:
    """`x is None` / `x is not None` and boolean combinations thereof."""
    if isinstance(test, ast.Compare):
        return all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops)
    if isinstance(test, ast.BoolOp):
        return all(_structural(v) for v in test.values)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _structural(test.operand)
    return False


def check(model):
    for fi in model.functions:
        mod = fi.module
        jit = fi.jit
        if jit is not None:
            scalars = _scalar_param_names(fi.node)
            for name, idx in scalars.items():
                if name in jit.static_names or (
                        idx is not None and idx in jit.static_nums):
                    continue
                yield Finding(
                    RULE, mod.relpath, fi.lineno, fi.node.col_offset,
                    f"jitted {fi.qualname} takes Python scalar `{name}` "
                    "without static_argnames/static_argnums: every distinct "
                    "value traces a new program",
                )
            static = set(jit.static_names)
            params = {p.arg for p in _params(fi.node)}
            traced = params - static - set(scalars)
            for node in ast.walk(fi.node):
                if isinstance(node, (ast.If, ast.While)):
                    test = node.test
                    if _structural(test):
                        continue
                    for sub in ast.walk(test):
                        if isinstance(sub, ast.Name) and sub.id in traced:
                            yield Finding(
                                RULE, mod.relpath, test.lineno,
                                test.col_offset,
                                f"Python branch on traced parameter "
                                f"`{sub.id}` inside jitted {fi.qualname}",
                            )
                            break
        # R3: inline jax.jit inside any non-__init__ body
        if fi.name in ("__init__", "__new__"):
            continue
        deco_nodes = set()
        for node in ast.walk(fi.node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for d in node.decorator_list:
                    deco_nodes.update(id(s) for s in ast.walk(d))
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call) and id(node) not in deco_nodes:
                info = jit_info_from_call(node)
                if info is not None and _is_inline_jit(node):
                    yield Finding(
                        RULE, mod.relpath, node.lineno, node.col_offset,
                        f"inline jax.jit inside {fi.qualname}: a fresh cache "
                        "entry per call; hoist to module scope or bind once",
                    )


def _is_inline_jit(call: ast.Call) -> bool:
    # partial(jax.jit, ...) used as a decorator factory is handled by the
    # decorator path; here we only flag direct jax.jit(fn, ...) calls
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "jit") or (
        isinstance(f, ast.Name) and f.id == "jit"
    )
