"""pallas-guard: every route into ``pl.pallas_call`` passes pallas_guarded.

The runtime contract (models/ivf.py:pallas_guarded): a Pallas kernel fault
must be attributed (bad kernel vs bad request), demoted one rung at a time
(nibble -> one-hot -> XLA), and never crash a serving request that the XLA
oracle could have answered. That only holds if NO public code path reaches
a kernel without the guard.

Static approximation (unit = every def/lambda, nested separately):

- A1: ``pl.pallas_call`` may only appear in kernel modules
  (``ops/*_pallas.py``) — kernels live with their VMEM budgets and
  interpret-mode fallbacks, not inline in model code.
- A2: taint = reaches-a-kernel. Seed: units containing ``pallas_call``.
  Propagate: a unit referencing a tainted unit (call or bare reference —
  passing a tainted function onward counts) becomes tainted, UNLESS the
  reference sits lexically inside the arguments of a guard-equivalent
  call, or the unit itself was defined inside such arguments (the lambdas
  handed to ``pallas_guarded`` run under the guard). Guard-equivalent:
  ``pallas_guarded``, any unit whose body calls ``pallas_guarded``
  (wrapper helpers like mesh.py's ``guarded``), and the reviewed ALLOW
  list (first-use oracle checks). Findings: tainted units with a public
  (non-underscore) name outside ``ops/``.

Name resolution follows Python scoping for bare names (a ``body`` helper
in one module never matches a ``body`` in another): own/ancestor nested
defs, then same-module top-level functions. ``self.x`` and
internal-module-alias attributes match repo units by name; calls through
external roots (``jax.*`` etc.) never do.
"""

import ast
from collections import defaultdict

from tools.graftlint.core import Finding, attr_root, call_name

RULE = "pallas-guard"

# reviewed guard-equivalent functions: these intentionally run kernels
# outside pallas_guarded (first-use oracle validation against the XLA path)
ALLOW = frozenset({"_validate_flat_pallas"})


def _kernel_module(mod) -> bool:
    return mod.relpath.endswith("_pallas.py") and (
        "/ops/" in mod.relpath or mod.relpath.startswith("ops/"))


def check(model):
    for u in model.units:
        if u.has_pallas_call and not _kernel_module(u.module):
            yield Finding(
                RULE, u.module.relpath, u.lineno, u.node.col_offset,
                f"pl.pallas_call in {u.qualname}: kernels belong in "
                "ops/*_pallas.py modules (VMEM budgets, interpret fallback, "
                "guard wiring live there)",
            )

    guard_names = {"pallas_guarded"} | set(ALLOW)
    for u in model.units:
        if u.calls_pallas_guarded and u.name:
            guard_names.add(u.name)

    children = defaultdict(list)
    toplevel = defaultdict(list)  # module -> units with no parent
    for u in model.units:
        if u.parent is not None:
            children[id(u.parent)].append(u)
        else:
            toplevel[id(u.module)].append(u)
    by_name_global = defaultdict(list)
    for u in model.units:
        if u.name:
            by_name_global[u.name].append(u)

    def bare_candidates(unit, name):
        cur = unit
        while cur is not None:
            local = [c for c in children[id(cur)] if c.name == name]
            if local:
                return local
            cur = cur.parent
        return [u for u in toplevel[id(unit.module)] if u.name == name]

    # pass 1: which def/lambda nodes sit inside guard-call arguments
    guarded_defsites = set()

    def mark_defsites(node, depth):
        extra = 0
        if isinstance(node, ast.Call) and call_name(node) in guard_names:
            extra = 1
        for child in ast.iter_child_nodes(node):
            d = depth + extra
            if isinstance(node, ast.Call) and extra and child is node.func:
                d = depth
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                if d > 0:
                    guarded_defsites.add(id(child))
                continue
            mark_defsites(child, d)

    for mod in model.modules:
        mark_defsites(mod.tree, 0)

    # pass 2: per-unit references (candidate units, guarded flag)
    refs = {}
    for u in model.units:
        out = []
        base_depth = 1 if id(u.node) in guarded_defsites else 0
        body = u.node.body if not isinstance(u.node, ast.Lambda) else [u.node.body]

        def visit(node, depth, u=u, out=out):
            extra = 0
            if isinstance(node, ast.Call) and call_name(node) in guard_names:
                extra = 1
            if isinstance(node, ast.Name) and node.id not in guard_names:
                cands = bare_candidates(u, node.id)
                if cands:
                    out.append((cands, depth > 0))
            elif (isinstance(node, ast.Attribute)
                    and node.attr not in guard_names):
                root = attr_root(node)
                if root in ("self", "cls") or (
                        root is not None and u.module.internal_alias(root)):
                    cands = by_name_global.get(node.attr)
                    if cands:
                        out.append((cands, depth > 0))
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue  # separate unit
                d = depth + extra
                if isinstance(node, ast.Call) and extra and child is node.func:
                    d = depth
                visit(child, d)

        for stmt in body:
            visit(stmt, base_depth)
        refs[u] = out

    # pass 3: fixpoint taint propagation
    tainted = {u for u in model.units if u.has_pallas_call}
    changed = True
    while changed:
        changed = False
        for u in model.units:
            if u in tainted or (u.name and u.name in guard_names):
                continue
            for cands, in_guard in refs[u]:
                if not in_guard and any(c in tainted for c in cands):
                    tainted.add(u)
                    changed = True
                    break

    for u in sorted(tainted, key=lambda u: (u.module.relpath, u.lineno)):
        if u.name is None:
            continue
        # public = importable surface: no underscore-prefixed component in
        # the qualified name (a helper nested in a private function is not
        # an entry point)
        if any(part.startswith("_") for part in u.qualname.split(".")):
            continue
        if _kernel_module(u.module) or u.module.is_ops():
            continue
        yield Finding(
            RULE, u.module.relpath, u.lineno, u.node.col_offset,
            f"public callable {u.qualname} reaches pl.pallas_call without "
            "going through pallas_guarded (no fault attribution / XLA "
            "demotion on kernel failure)",
        )
