"""CLI: ``python -m tools.graftlint [paths] [--format=text|json]``.

``--changed`` lints only the tracked-and-modified (plus untracked) .py
files under the default paths — ``git diff --name-only HEAD`` — which is
what scripts/precommit.sh runs so the growing checker suite stays fast
at commit time. Cross-artifact rules that need the whole package (the
PINS audit, the knob/doc drift check) gate themselves off on subsets;
CI still runs the full lint.

Exit status: 0 when clean, 1 when findings, 2 on usage errors. Runs
standalone (stdlib-only: ast) and under tier-1 via tests/test_graftlint.py
(the self-enforcing lint of the whole repo, marked ``lint``).
"""

import argparse
import json
import os
import subprocess
import sys

from tools.graftlint import DEFAULT_PATHS, __version__, lint_paths
from tools.graftlint import checks


def changed_files(paths=DEFAULT_PATHS):
    """Modified-vs-HEAD plus untracked .py files under ``paths``,
    as paths joined to the repo toplevel — ``git diff --name-only``
    emits repo-root-relative names, so resolving them against the cwd
    would silently lint nothing (and false-pass) when invoked from a
    subdirectory."""
    top = subprocess.run(["git", "rev-parse", "--show-toplevel"],
                         capture_output=True, text=True)
    if top.returncode != 0:
        raise RuntimeError(
            f"--changed needs a git checkout: {top.stderr.strip()}")
    root = top.stdout.strip()
    out = set()
    for cmd in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        # cwd=root: ls-files --others is otherwise cwd-relative AND
        # restricted to the cwd subtree
        proc = subprocess.run(cmd, capture_output=True, text=True, cwd=root)
        if proc.returncode != 0:
            raise RuntimeError(
                f"--changed needs a git checkout: {proc.stderr.strip()}")
        out.update(line.strip() for line in proc.stdout.splitlines()
                   if line.strip())
    prefixes = tuple(p.rstrip("/") + "/" for p in paths)
    return sorted(
        os.path.join(root, f) for f in out
        if f.endswith(".py") and (f.startswith(prefixes)
                                  or f in paths))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint",
        description="static analysis for JAX/Pallas/threading invariants",
    )
    parser.add_argument(
        "paths", nargs="*", default=list(DEFAULT_PATHS),
        help=f"files/directories to lint (default: {' '.join(DEFAULT_PATHS)})",
    )
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--list-rules", action="store_true",
                        help="print rule names and exit")
    parser.add_argument(
        "--changed", action="store_true",
        help="lint only files touched vs git HEAD (plus untracked) under "
             "the default paths — the precommit fast path")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, mod in sorted(checks.RULES.items()):
            doc = (mod.__doc__ or "").strip().splitlines()[0]
            print(f"{rule}: {doc}")
        return 0

    if args.changed:
        try:
            targets = changed_files()
        except RuntimeError as e:
            print(f"graftlint: {e}", file=sys.stderr)
            return 2
        if not targets:
            print("graftlint: no changed files under "
                  f"{' '.join(DEFAULT_PATHS)} — nothing to lint")
            return 0
        # subset lint: the rot audit and the knob/doc cross-check gate
        # themselves off (only decidable against the full package)
        findings = lint_paths(targets, subset=True)
    else:
        findings = lint_paths(args.paths)
    if args.format == "json":
        print(json.dumps(
            {
                "version": __version__,
                "count": len(findings),
                "findings": [f.to_dict() for f in findings],
            },
            indent=2,
        ))
    else:
        for f in findings:
            print(f)
        print(f"graftlint: {len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
