"""CLI: ``python -m tools.graftlint [paths] [--format=text|json]``.

``--changed`` lints only the tracked-and-modified (plus untracked) .py
files under the default paths — ``git diff --name-only HEAD`` — which is
what scripts/precommit.sh runs so the growing checker suite stays fast
at commit time. Cross-artifact rules that need the whole package (the
PINS audit, the knob/doc drift check) gate themselves off on subsets,
and the IR rules stay dormant (known, but never audited stale) on any
run without ``--ir``; CI still runs the full lint.

``--ir`` adds the IR tier: trace every utils/jitreg.py registry entry to
its ClosedJaxpr and run the equation-graph checkers, merged through the
same suppression/rot-audit pipeline. ``--ir-only`` runs just that tier
(suppressions still honored; the rot audit, undecidable without the AST
checkers, stays off). Both need jax importable — the plain AST lint
stays stdlib-only.

Exit status: 0 when clean, 1 when findings, 2 on usage errors. Runs
standalone (stdlib-only: ast) and under tier-1 via tests/test_graftlint.py
(the self-enforcing lint of the whole repo, marked ``lint``).
"""

import argparse
import json
import os
import subprocess
import sys

from tools.graftlint import DEFAULT_PATHS, __version__, lint_paths
from tools.graftlint import checks


def changed_files(paths=DEFAULT_PATHS):
    """Modified-vs-HEAD plus untracked .py files under ``paths``,
    as paths joined to the repo toplevel — ``git diff --name-only``
    emits repo-root-relative names, so resolving them against the cwd
    would silently lint nothing (and false-pass) when invoked from a
    subdirectory."""
    top = subprocess.run(["git", "rev-parse", "--show-toplevel"],
                         capture_output=True, text=True)
    if top.returncode != 0:
        raise RuntimeError(
            f"--changed needs a git checkout: {top.stderr.strip()}")
    root = top.stdout.strip()
    out = set()
    for cmd in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        # cwd=root: ls-files --others is otherwise cwd-relative AND
        # restricted to the cwd subtree
        proc = subprocess.run(cmd, capture_output=True, text=True, cwd=root)
        if proc.returncode != 0:
            raise RuntimeError(
                f"--changed needs a git checkout: {proc.stderr.strip()}")
        out.update(line.strip() for line in proc.stdout.splitlines()
                   if line.strip())
    prefixes = tuple(p.rstrip("/") + "/" for p in paths)
    return sorted(
        os.path.join(root, f) for f in out
        if f.endswith(".py") and (f.startswith(prefixes)
                                  or f in paths))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint",
        description="static analysis for JAX/Pallas/threading invariants",
    )
    parser.add_argument(
        "paths", nargs="*", default=list(DEFAULT_PATHS),
        help=f"files/directories to lint (default: {' '.join(DEFAULT_PATHS)})",
    )
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--list-rules", action="store_true",
                        help="print rule names and exit")
    parser.add_argument(
        "--changed", action="store_true",
        help="lint only files touched vs git HEAD (plus untracked) under "
             "the default paths — the precommit fast path")
    parser.add_argument(
        "--ir", action="store_true",
        help="also run the IR tier: trace the utils/jitreg.py registry "
             "entries and check the equation graphs (needs jax)")
    parser.add_argument(
        "--ir-only", action="store_true",
        help="run only the IR tier (suppressions honored, rot audit off)")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, mod in sorted(checks.RULES.items()):
            doc = (mod.__doc__ or "").strip().splitlines()[0]
            print(f"{rule}: {doc}")
        from tools.graftlint.core import IR_RULES
        from tools.graftlint import ir as ir_pkg
        docs = {}
        for ln in (ir_pkg.__doc__ or "").splitlines():
            ln = ln.strip()
            if ln.startswith("- ``ir-"):
                rule = ln.split("``")[1]
                docs[rule] = ln.split("—", 1)[-1].strip()
        for rule in sorted(IR_RULES):
            print(f"{rule}: [ir tier] {docs.get(rule, '')}")
        return 0

    ir_findings = None
    if args.ir or args.ir_only:
        try:
            from tools.graftlint.ir import lint_ir
        except ImportError as e:
            print(f"graftlint: --ir needs jax importable: {e}",
                  file=sys.stderr)
            return 2
        ir_findings = lint_ir()

    if args.ir_only:
        from tools.graftlint.core import build_model, lint
        # subset model: the rot audit is undecidable without the AST
        # checkers' findings, so it stays off — suppression matching for
        # the IR findings still applies
        model = build_model(args.paths, subset=True)
        findings = lint(model, ir_findings=ir_findings, ast_checks=False)
    elif args.changed:
        try:
            targets = changed_files()
        except RuntimeError as e:
            print(f"graftlint: {e}", file=sys.stderr)
            return 2
        if not targets:
            print("graftlint: no changed files under "
                  f"{' '.join(DEFAULT_PATHS)} — nothing to lint")
            return 0
        # subset lint: the rot audit and the knob/doc cross-check gate
        # themselves off (only decidable against the full package)
        findings = lint_paths(targets, subset=True, ir_findings=ir_findings)
    else:
        findings = lint_paths(args.paths, ir_findings=ir_findings)
    if args.format == "json":
        print(json.dumps(
            {
                "version": __version__,
                "count": len(findings),
                "findings": [f.to_dict() for f in findings],
            },
            indent=2,
        ))
    else:
        for f in findings:
            print(f)
        print(f"graftlint: {len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
