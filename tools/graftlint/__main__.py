"""CLI: ``python -m tools.graftlint [paths] [--format=text|json]``.

Exit status: 0 when clean, 1 when findings, 2 on usage errors. Runs
standalone (stdlib-only: ast) and under tier-1 via tests/test_graftlint.py
(the self-enforcing lint of the whole repo, marked ``lint``).
"""

import argparse
import json
import sys

from tools.graftlint import DEFAULT_PATHS, __version__, lint_paths
from tools.graftlint import checks


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint",
        description="static analysis for JAX/Pallas/threading invariants",
    )
    parser.add_argument(
        "paths", nargs="*", default=list(DEFAULT_PATHS),
        help=f"files/directories to lint (default: {' '.join(DEFAULT_PATHS)})",
    )
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--list-rules", action="store_true",
                        help="print rule names and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, mod in sorted(checks.RULES.items()):
            doc = (mod.__doc__ or "").strip().splitlines()[0]
            print(f"{rule}: {doc}")
        return 0

    findings = lint_paths(args.paths)
    if args.format == "json":
        print(json.dumps(
            {
                "version": __version__,
                "count": len(findings),
                "findings": [f.to_dict() for f in findings],
            },
            indent=2,
        ))
    else:
        for f in findings:
            print(f)
        print(f"graftlint: {len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
