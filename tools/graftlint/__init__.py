"""graftlint: repo-native static analysis for the JAX/Pallas/threading
invariants the serving hot path depends on.

Usage:
    python -m tools.graftlint [paths] [--format=json]

Library surface:
    from tools.graftlint import lint_paths, Finding
    findings = lint_paths(["distributed_faiss_tpu"])

Checkers, suppression syntax (``# graftlint: ok(<rule>)``) and the
hot-path/lock annotation conventions are documented in docs/LINTING.md.
"""

from tools.graftlint.core import Finding, lint_paths  # noqa: F401

__version__ = "0.4.0"  # 0.4: whole-program shared-state race detector (thread-root model + Eraser-style lockset analysis, atomic() markers + rot audit) alongside the DFT_RACECHECK runtime lockset witness

DEFAULT_PATHS = ("distributed_faiss_tpu", "tools")
