"""graftlint: repo-native static analysis for the JAX/Pallas/threading
invariants the serving hot path depends on.

Usage:
    python -m tools.graftlint [paths] [--format=json]

Library surface:
    from tools.graftlint import lint_paths, Finding
    findings = lint_paths(["distributed_faiss_tpu"])

Checkers, suppression syntax (``# graftlint: ok(<rule>)``) and the
hot-path/lock annotation conventions are documented in docs/LINTING.md.
"""

from tools.graftlint.core import Finding, lint_paths  # noqa: F401

__version__ = "0.5.0"  # 0.5: IR tier — jit-entry registry (utils/jitreg.py) traced to ClosedJaxprs with device-residency / accumulation-dtype / const-capture / bucket-budget checks, plus the DFT_XFERCHECK transfer-guard and DFT_COMPILECHECK compile-count runtime witnesses

DEFAULT_PATHS = ("distributed_faiss_tpu", "tools")
